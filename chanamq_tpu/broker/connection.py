"""Per-connection AMQP protocol engine.

Capability parity with the reference's FrameStage GraphStage
(chana-mq-server .../engine/FrameStage.scala:53-1297): protocol-header
handshake, SASL (PLAIN/EXTERNAL), tune negotiation, vhost open, channel
lifecycle, the full method dispatch (exchange/queue/basic/confirm/tx/access),
publish routing with mandatory/immediate returns, confirm-mode acks with
multiple-coalescing, QoS, ack/nack/reject/recover, heartbeats, and teardown
of exclusive queues on connection death.

Engine shape, by design (SURVEY.md §7.3 "pipelined command batching"): one
reader task processes commands strictly in order per connection; one writer
task drains an explicit output buffer (the reference's subtle isLastCommand
batching becomes trivially correct — everything appended between drains
coalesces into one TCP write). Delivery pushes come from queue dispatch
(event-driven), never from a poll tick.

Hot loop (_consume_scan): the native scanner hands back frame-index arrays
for a whole read chunk; contained Basic.Publish triples and Basic.Ack
frames are handled straight off the arrays with no Frame/Method/AMQCommand
objects (_fused_publish/_fused_ack), and everything else falls back to the
per-frame assembler path. Batch boundaries double as barriers: publisher
confirms, the store group-commit flush, and pipelined remote queue.push
RPCs all settle once per read batch (_confirm_barrier).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import time
import uuid
from typing import Optional

from ..amqp.command import AMQCommand, CommandAssembler
from ..amqp.constants import (
    ClassId,
    ErrorCode,
    FRAME_MIN_SIZE,
    FrameType,
    PROTOCOL_HEADER,
)
from ..amqp.frame import (
    Frame,
    FrameError,
    FrameParser,
    HEARTBEAT_BYTES,
    deliveries_wire_size,
    encode_deliveries,
)
from ..amqp import methods as am
from ..amqp.properties import BasicProperties
from ..amqp.frame import ENC_META as _ENC_META
from .. import events, profile, trace
from .broker import Broker, BrokerError
from .channel import ChannelMode, Consumer, ServerChannel
from ..flow import STAGE_THROTTLE

log = logging.getLogger("chanamq.connection")

from .. import __version__

SERVER_PROPERTIES = {
    "product": "chanamq-tpu",
    "version": __version__,
    "platform": "Python/asyncio",
    "capabilities": {
        "publisher_confirms": True,
        "basic.nack": True,
        "consumer_cancel_notify": True,
        "exchange_exchange_bindings": True,
    },
}

MECHANISMS = b"PLAIN EXTERNAL"
LOCALES = b"en_US"

# output buffer watermarks: above high, queue dispatch skips this connection's
# consumers; below low, dispatch resumes (SURVEY.md §7.3 "backpressure")
WRITE_HIGH_WATERMARK = 4 * 1024 * 1024
WRITE_LOW_WATERMARK = 1 * 1024 * 1024

# native batch egress: deliveries pending in a flush batch below this count
# render through the Python fallback — under ~4 records the ctypes argument
# marshalling costs more than the per-record b"".join it replaces
_EGRESS_MIN_BATCH = 4

# packed egress record meta (see native_ext._ENC_META): egress_deliver packs
# each record's header at buffer time so the flush is a single join + one
# native call with no per-record marshalling
_ENC_META_PACK = _ENC_META.pack
_ENC_META_UNPACK = _ENC_META.unpack

# scatter-gather egress: buffers per sendmsg call (Linux UIO_MAXIOV is 1024;
# stay under it and let the partial-write loop take further rounds)
_IOV_MAX = 512
_WRITEV_ENABLED = hasattr(os, "writev") and os.environ.get(
    "CHANAMQ_NATIVE_WRITEV", "1") not in ("0", "false", "no")

# method-frame payload prefixes the scan hot loop recognizes before any
# decode: Basic.Publish (class 60, method 40) and Basic.Ack (60, 80)
_PUBLISH_SIG = b"\x00\x3c\x00\x28"
_ACK_SIG = b"\x00\x3c\x00\x50"

# fused-path publish-args cache: a flow's exchange+routing-key repeat on
# every message, so their utf-8 decodes cache keyed by the raw args slice
# (everything past the 6 fixed bytes, bits included — plain publishes only
# reach the fused path, so bits are always 0). Churn-driven clears disable
# the cache for the process: per-message-unique routing keys must not pay
# cache overhead (same adaptive pattern as the client's deliver parse).
_PUBLISH_ARGS_CACHE: dict[bytes, tuple[str, str, bytes]] = {}
_PUBLISH_CACHE_STRIKES = 4
_publish_cache_strikes = 0

# fused-path content-header cache: a flow's publishes repeat the exact
# header payload (same properties, same body size), so the decoded
# BasicProperties caches keyed by the raw header bytes. The shared instance
# is safe: nothing mutates a decoded properties object (per-message state
# like published_ns lives on Message). Same adaptive churn-disable as the
# args cache — varying body sizes change the key, so mixed-size traffic
# self-disables instead of thrashing.
_HEADER_CACHE: dict[bytes, BasicProperties] = {}
_header_cache_strikes = 0


class ConnectionClosed(Exception):
    pass


class ChannelError(Exception):
    def __init__(self, code: ErrorCode, text: str, class_id: int = 0, method_id: int = 0):
        super().__init__(text)
        self.code = code
        self.text = text
        self.class_id = class_id
        self.method_id = method_id


class HardError(ChannelError):
    """Connection-level error: close the whole connection."""


class AMQPConnection:
    """One client connection being served."""

    _next_id = 1

    def __init__(
        self,
        broker: Broker,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        heartbeat_s: int = 30,
        frame_max: int = 131072,
        channel_max: int = 2047,
        max_message_size: int = 128 * 1024 * 1024,
        users: Optional[dict[str, str]] = None,
        permissions: Optional[dict[str, list[str]]] = None,
    ) -> None:
        self.broker = broker
        self.reader = reader
        self.writer = writer
        self.id = AMQPConnection._next_id
        AMQPConnection._next_id += 1

        self.cfg_heartbeat = heartbeat_s
        self.cfg_frame_max = frame_max
        self.cfg_channel_max = channel_max
        self.heartbeat_s = 0
        self.frame_max = frame_max
        self.channel_max = channel_max

        self.users = users  # None: accept anything (reference parity)
        self.permissions = permissions  # per-user vhost allowlists
        self.username: Optional[str] = None
        self.vhost_name: str = ""
        self.channels: dict[int, ServerChannel] = {}
        # channels we soft-closed: frames on them are discarded until the
        # client's Channel.CloseOk arrives (0-9-1 close protocol)
        self._closing_channels: set[int] = set()
        self.exclusive_queues: set[str] = set()
        # monotonic per-connection counters: the telemetry sampler derives
        # per-connection publish/deliver/ack rates from their deltas
        self.published_msgs = 0
        self.delivered_msgs = 0
        self.acked_msgs = 0
        self.closing = False
        self.closed = asyncio.get_event_loop().create_future()

        from .. import native_ext

        if native_ext.available():
            self._parser: FrameParser = native_ext.NativeFrameParser()
        else:
            self._parser = FrameParser()
        # cap declared content size: body chunks buffer in the assembler
        # before a command exists, so resident-memory backpressure can't
        # see them (chana.mq.message.max-size; RabbitMQ's analogue caps
        # at 512 MiB, default 128 MiB)
        self._assembler = CommandAssembler(max_body_size=max_message_size)
        # output path: a list of pending wire buffers (bytes appended via
        # send_bytes coalesce into a bytearray tail; batch-encoded egress
        # appends pooled memoryviews) drained by the writer task as ONE
        # scatter-gather sendmsg per wakeup. _out_bytes tracks the list's
        # total so the watermarks stay O(1); _out_pooled holds the arena
        # slot ids riding in _out, released once the kernel write lands.
        self._out: list = []
        self._out_bytes = 0
        self._out_pooled: list[int] = []
        self._out_event = asyncio.Event()
        # raw socket for the scatter-gather writer (resolved in serve();
        # None = TLS or non-socket transport, writer falls back to
        # join + StreamWriter.write)
        self._sock = None
        # native batch egress: deliveries buffered as flat packed parts
        # (_ENC_META header + prefix/exrk/header/body slices, 5 parts per
        # record) and rendered in one chana_encode_deliveries_packed call
        # at the dispatch-pass flush (or the call_soon guard for
        # off-dispatch paths: streams, cluster stubs)
        self._egress = broker.egress_encoder
        self._egress_pending: list = []
        self._egress_records = 0
        self._egress_bytes = 0
        self._egress_guard_scheduled = False
        self._writer_task: Optional[asyncio.Task] = None
        self._heartbeat_task: Optional[asyncio.Task] = None
        self._last_recv = time.monotonic()
        self._last_send = time.monotonic()
        # publish-timestamp ring for latency measurement (confirm-less)
        self._authenticated = False
        self._tuned = False
        self._opened = False
        # confirm coalescing: channel id -> highest publish seq completed in
        # the current read batch; flushed as one Basic.Ack(multiple) per batch
        self._pending_confirms: dict[int, int] = {}
        # store-op enqueue windows (store.mark() pairs) covering THIS
        # connection's confirmed persistent publishes; passed to
        # flush(intervals=...) so the barrier fails only for our own writes
        self._confirm_marks: list[tuple[int, int]] = []
        # backpressure marker: only connections that have published can have
        # work held at the broker gate (consumer-only connections are never
        # touched by it)
        self._has_published = False
        # publish-hold backpressure (VERDICT r4 weak #2, reworked after
        # review): while the broker gate is closed, Basic.Publish commands
        # are HELD per channel instead of executed — and once a channel
        # holds a publish, everything behind it on that channel holds too
        # (per-channel FIFO). Every other frame keeps processing, so acks
        # still drain the gate (no deadlock), heartbeats/EOF stay
        # observable (the reaper keeps working), and a flooder gains
        # nothing from a token consumer. Held bodies are capped at
        # PARK_BUF_MAX bytes and accounted against the memory gauge; at
        # the cap the connection stops being read (real TCP backpressure)
        # with a bounded liveness grace (_park_full_since).
        self._held: dict[int, list] = {}
        self._held_bytes = 0
        self._park_full_since: Optional[float] = None
        # flow-ladder per-connection state: publish credit remaining while
        # the broker throttles (lazily granted from broker.flow_publish_credit
        # at the first gated publish; None = no grant outstanding), the
        # channels we sent Channel.Flow(active=false) to, and the
        # perf-counter stamp of the first hold in the current park episode
        # (feeds the flow-throttle trace span)
        self._flow_credit: Optional[int] = None
        self._flow_stopped: set[int] = set()
        self._park_t0: Optional[int] = None
        # client announced capabilities.connection.blocked in start-ok:
        # it wants Connection.Blocked/Unblocked notifications
        self._supports_blocked = False
        # capabilities.consumer_cancel_notify: the client wants a server-
        # sent Basic.Cancel when a queue dies under its consumer
        self._supports_cancel_notify = False
        # frames the current _fused_publish covered (so _consume_scan's
        # soft-error handlers resume past the failed publish's frames)
        self._fused_skip = 0
        # buffered remote push records from this read batch (clustered
        # pipelined publishes) — sent as one queue.push_many per owner and
        # awaited at the batch barrier. _remote_strict marks that at least
        # one buffered record came from a confirm-armed publish: only then
        # does a drain failure escalate to a connection error (best-effort
        # publishes just log, like the pre-pipelining inline path)
        self._remote_pending: list = []
        # single-node twin of _remote_pending: fused publishes deferred for
        # the tensor router (chana.mq.router.*) — flushed synchronously
        # before ANY other command, publish, confirm release, or close, so
        # per-channel/per-queue FIFO and confirm durability are preserved
        # exactly as if each message had published inline
        self._route_pending: list = []
        self._remote_strict = False
        self._remote_failures: list = []
        # tail of the ordered background chain pipelining remote-push
        # round trips past the read loop (see _batch_barrier)
        self._remote_chain: Optional[asyncio.Task] = None
        # multi-tenancy (chanamq_tpu/tenancy/): resolved once at
        # Connection.Open from broker.tenancy. _throttled is the tenant's
        # publish gate (token bucket drained / memory-share floor) and
        # rides the same hold machinery as broker.blocked; _tenant_rated
        # is the Tenant object ONLY when its quota declares a
        # publish-rate, so the ungated publish hot path pays one
        # attribute load + None check. ACL booleans are per-connection
        # constants (user x vhost is fixed after Open); _can_write also
        # gates the fused fast path so denials surface as proper 403s.
        self.tenant = None
        self._tenant_rated = None
        self._throttled = False
        self._can_configure = True
        self._can_write = True
        self._can_read = True

    # ------------------------------------------------------------------
    # output path
    # ------------------------------------------------------------------

    @property
    def write_saturated(self) -> bool:
        return self._out_bytes + self._egress_bytes >= WRITE_HIGH_WATERMARK

    def send_bytes(self, data: bytes) -> None:
        if self.closing:
            return
        if self._egress_pending:
            # wire-order invariant: buffered deliveries precede any frame
            # rendered after them (confirms, method replies, heartbeats)
            self.flush_egress()
        out = self._out
        if out and type(out[-1]) is bytearray:
            out[-1] += data
        else:
            out.append(bytearray(data))
        self._out_bytes += len(data)
        self._out_event.set()

    def send_command(self, command: AMQCommand) -> None:
        self.send_bytes(command.render(self.frame_max))

    def send_method(self, channel: int, method: am.Method) -> None:
        self.send_bytes(Frame.method(channel, method.encode()).to_bytes())

    # -- native batch egress -------------------------------------------

    def egress_deliver(self, channel_id: int, prefix: bytes, tag: int,
                       redelivered: bool, exrk: bytes, header: bytes,
                       body: bytes) -> None:
        """Buffer one basic.deliver as packed parts instead of rendering
        it: the whole batch renders in one native
        chana_encode_deliveries_packed call at the flush point
        (dispatch-pass end for classic queues — inside the
        dispatch/deliver ledger window — or the call_soon guard for
        stream/cluster delivery paths)."""
        pend = self._egress_pending
        if not pend:
            self.broker.egress_dirty.add(self)
            if not self._egress_guard_scheduled:
                self._egress_guard_scheduled = True
                asyncio.get_event_loop().call_soon(self._egress_guard)
        plen = len(prefix)
        elen = len(exrk)
        hlen = len(header)
        blen = len(body)
        pend += (_ENC_META_PACK(channel_id, tag, 1 if redelivered else 0,
                                plen, elen, hlen, blen),
                 prefix, exrk, header, body)
        self._egress_records += 1
        # exact wire size, tracked so write_saturated (dispatch
        # backpressure) sees buffered records the moment they queue
        size = 25 + plen + elen + hlen
        if blen:
            frame_max = self.frame_max
            if frame_max:
                size += blen + 8 * -(-blen // (frame_max - 8))
            else:
                size += blen + 8
        self._egress_bytes += size

    def _egress_guard(self) -> None:
        # safety net for deliveries buffered outside a queue dispatch pass
        # (stream cursors, cluster stub renders): runs on the next loop
        # iteration, after the dispatch-end flush has usually already
        # drained the batch
        self._egress_guard_scheduled = False
        if self._egress_pending:
            self.flush_egress()

    def flush_egress(self) -> None:
        """Render the buffered delivery records into the output list: one
        native batch encode into a pooled arena buffer when the batch is
        worth it, the pure-Python encode_deliveries fallback otherwise.
        Synchronous — callable from any point of dispatch or batch
        processing without yielding the loop."""
        pend = self._egress_pending
        if not pend:
            return
        self._egress_pending = []
        nrec = self._egress_records
        self._egress_records = 0
        nbytes = self._egress_bytes
        self._egress_bytes = 0
        self.broker.egress_dirty.discard(self)
        if self.closing:
            return
        metrics = self.broker.metrics
        enc = self._egress
        buf = None
        slot = -1
        if enc is not None and nrec >= _EGRESS_MIN_BATCH:
            res = enc.encode_packed(pend, nrec, self.frame_max, nbytes)
            if res is not None:
                buf, slot = res
                if slot < 0 and nbytes > enc.buf_bytes:
                    # oversized batch went to the heap by design, not
                    # because the arena ran dry
                    pass
                elif slot < 0:
                    metrics.native_pool_exhausted += 1
            else:  # pragma: no cover - size-mismatch defense
                metrics.native_egress_fallbacks += 1
        if buf is None:
            # small batch / no encoder: rebuild records off the packed
            # parts (5 per record) for the pure-Python renderer
            records = []
            for j in range(0, len(pend), 5):
                cid, tag, red, _pl, _el, _hl, _bl = _ENC_META_UNPACK(pend[j])
                records.append((cid, pend[j + 1], tag, red, pend[j + 2],
                                pend[j + 3], pend[j + 4]))
            buf = encode_deliveries(records, self.frame_max)
        else:
            metrics.native_egress_batches += 1
            metrics.native_egress_msgs += nrec
            metrics.native_egress_bytes += nbytes
        out = self._out
        if slot >= 0:
            self._out_pooled.append(slot)
            out.append(buf)
        elif type(buf) is bytearray:
            out.append(buf)  # native heap encode: already its own buffer
        elif out and type(out[-1]) is bytearray:
            out[-1] += buf
        else:
            out.append(bytearray(buf))
        self._out_bytes += nbytes
        self._out_event.set()

    # -- writer task ----------------------------------------------------

    async def _writer_loop(self) -> None:
        try:
            while True:
                await self._out_event.wait()
                self._out_event.clear()
                if self._out:
                    bufs = self._out
                    pooled = self._out_pooled
                    nbytes = self._out_bytes
                    self._out = []
                    self._out_pooled = []
                    self._out_bytes = 0
                    was_saturated = nbytes >= WRITE_HIGH_WATERMARK
                    try:
                        await self._write_bufs(bufs)
                    finally:
                        # arena slots return to the pool even when the
                        # write dies mid-flight (connection teardown
                        # awaits/cancels this task before closing)
                        if pooled:
                            enc = self._egress
                            for slot in pooled:
                                enc.release(slot)
                    self._last_send = time.monotonic()
                    if not self._out and self.broker.flow_consumer_buffer:
                        # fully drained to the kernel: whatever this
                        # connection's consumers had buffered is on the
                        # wire — reset their delivery-buffer accounting
                        self._reset_consumer_buffers()
                    if was_saturated and (self._out_bytes
                                          < WRITE_LOW_WATERMARK):
                        self._resume_dispatch()
                if self.closing and not self._out:
                    break
        except (ConnectionResetError, BrokenPipeError, OSError, ValueError,
                asyncio.CancelledError):
            # dead peer (or the socket closed under us mid-write): mark
            # closing so a main loop parked at the memory gate (not
            # reading, hence blind to the hangup) still exits
            self.closing = True

    async def _write_bufs(self, bufs: list) -> None:
        """One writer wakeup's kernel hand-off: scatter-gather writev of
        the pending buffer list on plain TCP/UDS sockets (no concatenation
        copy), StreamWriter.write + drain otherwise (TLS, test doubles).

        asyncio forbids a second add_writer on a transport-owned fd, so a
        full kernel buffer (EAGAIN) spills the remainder into the transport
        — which owns the fd's writability callback — and writev resumes
        once the transport reports its buffer drained."""
        sock = self._sock
        if sock is None or self.writer.transport.get_write_buffer_size():
            self.writer.write(b"".join(bufs))
            await self.writer.drain()
            return
        fd = sock.fileno()
        idx = 0
        total = len(bufs)
        while idx < total:
            batch = bufs[idx:idx + _IOV_MAX]
            try:
                sent = os.writev(fd, batch)
            except InterruptedError:
                continue
            except BlockingIOError:
                self.writer.write(b"".join(bufs[idx:]))
                await self.writer.drain()
                return
            while sent > 0:
                blen = len(bufs[idx])
                if sent >= blen:
                    sent -= blen
                    idx += 1
                else:
                    # partial buffer: keep the unsent tail (memoryview
                    # slicing is zero-copy for both bytearray and pooled
                    # arena buffers)
                    mv = bufs[idx]
                    if type(mv) is not memoryview:
                        mv = memoryview(mv)
                    bufs[idx] = mv[sent:]
                    sent = 0

    def _resume_dispatch(self) -> None:
        for channel in self.channels.values():
            for consumer in channel.consumers.values():
                consumer.queue.schedule_dispatch()

    def _reset_consumer_buffers(self) -> None:
        """Output buffer hit the kernel: clear per-consumer delivery-buffer
        bytes and wake dispatch for any consumer that was marked slow."""
        for channel in self.channels.values():
            for consumer in channel.consumers.values():
                if consumer.buffered_bytes:
                    consumer.buffered_bytes = 0
                    if consumer.slow:
                        consumer.slow = False
                        consumer.queue.schedule_dispatch()

    # ------------------------------------------------------------------
    # main loop
    # ------------------------------------------------------------------

    async def serve(self) -> None:
        """Run the connection to completion."""
        self.broker.metrics.connections_opened += 1
        sock = self.writer.get_extra_info("socket")
        if sock is not None and hasattr(sock, "setsockopt"):
            try:
                # disable Nagle: deliver/confirm frames are small writes
                # and must not wait on the peer's delayed ACK (the batch
                # egress already coalesces a dispatch pass into one
                # writev, so there is nothing left for Nagle to batch)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # unix socket / exotic family: no Nagle to disable
        if _WRITEV_ENABLED and self.writer.get_extra_info("ssl_object") is None:
            # plain TCP/UDS stream: the writer drains via scatter-gather
            # sendmsg on the raw socket (every steady-state byte goes
            # through _out, so the transport's own buffer stays empty and
            # direct socket writes can't interleave with it)
            if sock is not None and hasattr(sock, "fileno"):
                self._sock = sock
        self._writer_task = asyncio.create_task(self._writer_loop())
        self.broker.blocked_listeners.add(self._on_memory_blocked)
        self.broker.flow_stage_listeners.add(self._on_flow_stage)
        self.broker.connections.add(self)
        try:
            await self._handshake()
            bus = events.ACTIVE
            if bus is not None:
                bus.emit("connection.created", {
                    "connection": self.id, "vhost": self.vhost_name,
                    "user": self.username,
                })
            await self._main_loop()
        except ConnectionClosed:
            pass
        except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("connection %d crashed", self.id)
        finally:
            self.broker.blocked_listeners.discard(self._on_memory_blocked)
            self.broker.flow_stage_listeners.discard(self._on_flow_stage)
            self.broker.connections.discard(self)
            await self._teardown()

    def _on_memory_blocked(self, blocked: bool) -> None:
        """Broker memory gate transition: notify clients that announced the
        connection.blocked capability (exceeds the reference, which never
        implemented Blocked/Unblocked — README.md:10-22)."""
        if self._supports_blocked and self._opened and not self.closing:
            if blocked:
                self.send_method(0, am.Connection.Blocked(
                    reason=self.broker.blocked_reason))
            else:
                self.send_method(0, am.Connection.Unblocked())

    def _on_flow_stage(self, old: int, new: int) -> None:
        """Flow-ladder transition (stage 2 = throttle): surface publisher
        throttling on the wire as server-initiated Channel.Flow. Publishers
        that honor it stop sending voluntarily; ones that don't hit the
        park/credit path anyway (Flow is advisory, the hold is the law).
        Consumer-only connections are left alone — pausing them would slow
        the very drain that reopens the gate."""
        if self.closing or not self._opened:
            return
        if new >= STAGE_THROTTLE and old < STAGE_THROTTLE:
            if not self._has_published:
                return
            for channel_id, channel in self.channels.items():
                if channel_id not in self._closing_channels:
                    self.send_method(channel_id, am.Channel.Flow(active=False))
                    self._flow_stopped.add(channel_id)
            if self._flow_stopped:
                self.broker.metrics.flow_throttles += 1
        elif new < STAGE_THROTTLE and old >= STAGE_THROTTLE:
            if self._throttled:
                return  # tenant gate still closed: keep publishers stopped
            resumed = False
            for channel_id in self._flow_stopped:
                if (channel_id in self.channels
                        and channel_id not in self._closing_channels):
                    self.send_method(channel_id, am.Channel.Flow(active=True))
                    resumed = True
            self._flow_stopped.clear()
            if resumed:
                self.broker.metrics.flow_resumes += 1

    def set_tenant_gate(self, on: bool) -> None:
        """Tenant publish-gate transition (token bucket drained or
        memory-share floor hit, tenancy/registry.py). Mirrors
        _on_flow_stage: the gate itself is the hold interception in
        _run_command / the fused-path check; Channel.Flow is the advisory
        wire signal for publishers that honor it."""
        if on == self._throttled:
            return
        self._throttled = on
        if self.closing or not self._opened:
            return
        if on:
            if not self._has_published:
                return
            for channel_id in self.channels:
                if channel_id not in self._closing_channels:
                    self.send_method(channel_id, am.Channel.Flow(active=False))
                    self._flow_stopped.add(channel_id)
        else:
            if self.broker.blocked:
                return  # broker ladder still throttling: keep them stopped
            for channel_id in self._flow_stopped:
                if (channel_id in self.channels
                        and channel_id not in self._closing_channels):
                    self.send_method(channel_id, am.Channel.Flow(active=True))
            self._flow_stopped.clear()

    def detach_tenant(self) -> None:
        """Tenant removed at runtime: the connection stays open but loses
        quota/ACL scoping (its vhost is no longer tenant-owned)."""
        if self._throttled:
            self.set_tenant_gate(False)
        self.tenant = None
        self._tenant_rated = None
        self._can_configure = self._can_write = self._can_read = True

    def notify_consumer_cancel(self, channel: ServerChannel, tag: str) -> None:
        """Server-sent Basic.Cancel: the queue died under this consumer
        (delete / auto-delete / exclusive death / idle expiry). Sent only
        to clients that announced the consumer_cancel_notify capability
        (RabbitMQ extension; EXCEEDS the reference, which never cancels)."""
        if (self._supports_cancel_notify and not self.closing
                and not channel.closed):
            self.send_method(channel.id, am.Basic.Cancel(
                consumer_tag=tag, nowait=True))

    # held-publish byte cap per connection: one read chunk. Checked between
    # chunks, so the effective bound is cap + one chunk; past it the peer
    # is genuinely backpressured (TCP window closes) and unobservable.
    PARK_BUF_MAX = 262144
    # flat per-held-command cost added to the body bytes (AMQCommand +
    # method + properties object overhead): bounds the held-command COUNT
    # for empty/tiny-body floods, not just the byte volume
    HELD_COMMAND_OVERHEAD = 512
    # multiple of the heartbeat interval a full-buffer (unobservable) peer
    # keeps its liveness clock refreshed; past it the heartbeat reaper's
    # normal 2x-interval deadline applies even while the broker is gated
    PARK_FULL_GRACE_INTERVALS = 4

    def _park_grace_tick(self) -> None:
        """Liveness bookkeeping while reads are stopped at the held-buffer
        cap. Pending bytes prove the peer was alive recently, so the clock
        is refreshed — but only for a bounded grace: an unobservable peer
        must not dodge the reaper forever (a dead flooder would otherwise
        linger until kernel retransmit timeout, VERDICT r4 weak #3)."""
        now = time.monotonic()
        if self._park_full_since is None:
            self._park_full_since = now
        grace = self.PARK_FULL_GRACE_INTERVALS * max(self.heartbeat_s, 1)
        if now - self._park_full_since < grace:
            self._last_recv = now

    def _hold_command(self, command: AMQCommand) -> None:
        """Park one command behind the publisher gate (publishes, and
        anything pipelined behind a held publish on the same channel)."""
        if type(command.method) is am.Basic.Publish:
            self._has_published = True  # set at hold time too: a fully-held
            # publisher must still read as a publisher everywhere the flag
            # is consulted
        if self._park_t0 is None:
            # first hold of this park episode: start the flow-throttle span
            self._park_t0 = time.perf_counter_ns()
        self._held.setdefault(command.channel, []).append(command)
        # cost = body + flat per-command overhead, so a flood of empty-body
        # publishes (legal AMQP) still trips the cap instead of accumulating
        # unbounded AMQCommand objects past a body-only count
        cost = self._held_cost(command)
        self._held_bytes += cost
        # tracked on a SEPARATE gauge, not resident_bytes: held bodies
        # gating their own release would deadlock the gate (they only
        # leave RAM by being released below the low watermark). They
        # are structurally bounded instead: PARK_BUF_MAX per
        # connection x the listener's max-connections cap. The flow
        # accountant counts them in the reported total but excludes them
        # from ladder decisions for the same deadlock reason.
        self.broker.account_held(cost)

    @classmethod
    def _held_cost(cls, command: AMQCommand) -> int:
        return len(command.body or b"") + cls.HELD_COMMAND_OVERHEAD

    def _held_cap(self) -> int:
        """Hold budget before reads stop. A connection with outstanding
        deliveries gets 4x: its acks — the very thing that drains the gate
        — may be pipelined behind a burst of publishes, and stopping reads
        at the base cap would wedge them unread (a worker publishing and
        consuming on one connection would deadlock its own gate). Still a
        hard bound: a flooder parking one unacked delivery as a hostage
        buys 4x PARK_BUF_MAX, not an unbounded hold, and the ack-timeout
        sweep eventually closes channels that never ack."""
        base = self.broker.park_buf_max or self.PARK_BUF_MAX
        for channel in self.channels.values():
            if channel.unacked:
                return 4 * base
        return base

    def _should_hold(self, command: AMQCommand) -> bool:
        method_type = type(command.method)
        if method_type in (am.Basic.Ack, am.Basic.Nack, am.Basic.Reject):
            # settles of PRIOR deliveries commute with held publishes
            # (delivery tags are independent of the publish stream) and are
            # exactly what must keep draining the gate: holding a
            # same-channel ack behind a held publish would deadlock a
            # single-channel publish+consume client against its own gate
            return False
        if command.channel in self._held:
            return True  # per-channel FIFO behind an already-held publish
        if method_type is am.Basic.Publish and command.channel != 0:
            if self.broker.blocked:
                # per-connection publish credit
                # (chana.mq.flow.publish-credit): the first gated publishes
                # spend a bounded byte allowance before the hard hold
                # engages, so a well-behaved publisher that reacts to
                # Channel.Flow(active=false) in time never parks at all.
                # Credit 0 (the Broker default) holds immediately — the
                # legacy gate contract.
                return not self._spend_flow_credit(command)
            if self._throttled:
                return not self._spend_tenant_credit(command)
        return False

    def _spend_flow_credit(self, command: AMQCommand) -> bool:
        """Spend publish credit for one gated publish; True while credit
        remains (the publish executes instead of holding). The grant is
        lazy — taken from the broker knob at the first gated publish of a
        throttle episode — and reset when the gate reopens."""
        grant = self.broker.flow_publish_credit
        if not grant:
            return False
        if self._flow_credit is None:
            self._flow_credit = grant
        if self._flow_credit <= 0:
            return False
        self._flow_credit -= self._held_cost(command)
        return True

    def _spend_tenant_credit(self, command: AMQCommand) -> bool:
        """Tenant-gated twin of _spend_flow_credit: while the tenant's
        publish gate is closed, the per-connection credit grant is drawn
        from whatever tokens the tenant's bucket has re-accrued (capped at
        the broker's flow grant), so the held stream drains at exactly the
        quota rate instead of stalling until a full resume. Executed
        publishes that pass here are pre-paid — the publish-site spend is
        skipped while _throttled (see _tenant_spend)."""
        tenant = self.tenant
        if tenant is None or tenant.memory_gated:
            # no tenant (gate mid-lift) executes; a memory-share floor
            # never grants — only draining lifts it
            return tenant is None
        if not self._flow_credit:  # None or spent: draw a fresh grant
            grant = tenant.take_credit(
                self.broker.flow_publish_credit or self.PARK_BUF_MAX)
            if grant <= 0:
                return False
            self._flow_credit = grant
        self._flow_credit -= self._held_cost(command)
        return True

    def _tenant_spend(self, nbytes: int) -> None:
        """Publish-site token spend (generic + fast paths; the fused path
        inlines the same two lines). Accounted cost matches the held-cost
        formula (body + flat per-command overhead) so empty-body floods
        still drain the bucket. Skipped while gated: gated publishes that
        execute pre-paid via _spend_tenant_credit."""
        rated = self._tenant_rated
        if rated is not None and not self._throttled:
            rated.spend(nbytes + self.HELD_COMMAND_OVERHEAD)

    async def _release_held(self) -> bool:
        """Gate reopened: execute held commands, per-channel FIFO (channel
        interleaving is free under AMQP). If the gate closes again
        mid-release, the remainder re-holds via the normal interception.
        Returns False when the connection must stop serving."""
        held, self._held = self._held, {}
        self._held_bytes = 0
        self._park_full_since = None
        self._flow_credit = None  # fresh grant next throttle episode
        if self._park_t0 is not None:
            t0, self._park_t0 = self._park_t0, None
            t1 = time.perf_counter_ns()
            self.broker.metrics.flow_hold_releases += 1
            self.broker.metrics.flow_hold_wait_ns += t1 - t0
            prof = profile.ACTIVE
            if prof is not None:
                # wall, not CPU: how long the gate parked this stream —
                # one accumulate per throttle episode, already-stamped
                prof.stage_ns[profile.FLOW_THROTTLE] += t1 - t0
                prof.stage_calls[profile.FLOW_THROTTLE] += 1
            if trace.ACTIVE is not None:
                # the first released publish carries the flow-throttle span
                # (how long the gate parked this connection's stream)
                trace.ACTIVE.flow_ns = (t0, t1)
        queues = list(held.values())
        for qi, commands in enumerate(queues):
            for ci, command in enumerate(commands):
                self.broker.account_held(-self._held_cost(command))
                if not await self._run_command(command):
                    # connection is stopping: release the gauge for every
                    # command not yet un-accounted (none were confirmed —
                    # seqs are assigned at execution time)
                    for rest in commands[ci + 1:]:
                        self.broker.account_held(-self._held_cost(rest))
                    for later in queues[qi + 1:]:
                        for rest in later:
                            self.broker.account_held(-self._held_cost(rest))
                    return False
        # same barrier as the main loop: confirms for persistent publishes
        # must not ack until their store writes are flushed (a barrier
        # failure propagates and tears the connection down, like there)
        await self._confirm_barrier()
        self._flush_confirms()
        return True

    async def _read_chunk(self) -> bytes:
        # large reads amortize event-loop wakeups and process context
        # switches (one core may run broker + many clients); at ~170 wire
        # bytes per small publish this is ~1500 messages per syscall
        data = await self.reader.read(262144)
        if not data:
            raise ConnectionClosed()
        self._last_recv = time.monotonic()
        if trace.ACTIVE is not None:
            # ingress-parse spans start at the chunk read; one stamp per
            # ~256 KiB read, not per message (begin_publish drops it when
            # stale, e.g. an idle connection)
            trace.ACTIVE.ingress_ns = time.perf_counter_ns()
        return data

    async def _handshake(self) -> None:
        """Protocol header exchange (reference: FrameStage.scala:181-234)."""
        header = await self.reader.readexactly(8)
        self._last_recv = time.monotonic()
        if header != PROTOCOL_HEADER:
            # wrong protocol: reply with ours and hang up
            self.writer.write(PROTOCOL_HEADER)
            await self.writer.drain()
            raise ConnectionClosed()
        self.send_method(0, am.Connection.Start(
            version_major=0, version_minor=9,
            server_properties=SERVER_PROPERTIES,
            mechanisms=MECHANISMS, locales=LOCALES,
        ))

    async def _main_loop(self) -> None:
        # the native parser exposes the raw scan arrays: the hot loop walks
        # them directly (fused publish path); the pure-Python parser keeps
        # the Frame-object path
        scan = getattr(self._parser, "scan_batches", None)
        while not self.closing:
            if self._held and not self.broker.blocked and not self._throttled:
                # gate reopened: run the held publishes (per-channel FIFO)
                if not await self._release_held():
                    return
                continue
            # held-buffer cap reached while the gate is closed: stop
            # reading (bytes back up into TCP). Liveness is unobservable
            # in this state, so the clock gets a BOUNDED grace — a peer
            # that stays unobservable past it is reaped by the heartbeat
            # loop (VERDICT r4 weak #3: the grace must be capped). The
            # tenant gate has no event to wait on (it lifts on the next
            # registry tick), so its park leg is a bounded sleep.
            while ((self.broker.blocked or self._throttled)
                   and not self.closing
                   and self._held_bytes >= self._held_cap()):
                self._park_grace_tick()
                if self.broker.blocked:
                    await self.broker.wait_memory_gate()
                else:
                    await asyncio.sleep(0.25)
            if self.closing:
                return
            if self._held and not self.broker.blocked and not self._throttled:
                continue  # gate just reopened: release before reading more
            if self._held:
                # bounded read while holding: the loop must wake to release
                # held commands once the gate reopens even if the peer
                # sends nothing further (a blocking read would deadlock
                # the release against the peer's silence)
                try:
                    data = await asyncio.wait_for(self._read_chunk(), 0.25)
                except asyncio.TimeoutError:
                    continue
            else:
                data = await self._read_chunk()
            # one ingress-cycle ledger window per read chunk: parse walk,
            # fused publishes, command dispatch, and the batch barrier all
            # run inside it (two stamps per ~256 KiB chunk, not per
            # message) — this is the top-level "where did the loop's CPU
            # go" stage the finer route/enqueue stages nest within. The
            # window is loop-thread CPU, and any OTHER top-level window
            # that accumulated while this coroutine was suspended (a
            # dispatch pass, a sibling connection's cycle) is subtracted
            # back out so the top-level sum never double-counts.
            prof = profile.ACTIVE
            if prof is not None:
                sns = prof.stage_ns
                t_cycle = time.thread_time_ns()
                nested0 = int(sns[profile.DISPATCH]
                              + sns[profile.CLUSTER_PUSH]
                              + sns[profile.INGRESS_CYCLE])
            if scan is not None:
                ok = await self._consume_scan(scan(data))
            else:
                ok = await self._consume_feed(self._parser.feed(data))
            if ok:
                await self._batch_barrier()
            if prof is not None:
                dt = time.thread_time_ns() - t_cycle
                nested = int(sns[profile.DISPATCH]
                             + sns[profile.CLUSTER_PUSH]
                             + sns[profile.INGRESS_CYCLE]) - nested0
                if dt > nested:
                    sns[profile.INGRESS_CYCLE] += dt - nested
                prof.stage_calls[profile.INGRESS_CYCLE] += 1
            if not ok:
                return

    async def _run_command(self, out: AMQCommand) -> bool:
        """Dispatch one assembled command with the connection's error
        semantics. Returns False when the connection must stop serving."""
        if (self.broker.flow_refusing
                and type(out.method) is am.Basic.Publish
                and out.channel != 0
                and out.channel not in self._held):
            # ladder stage 4 (refuse): past the refuse watermark, fresh
            # publishes are rejected outright with a channel-level
            # precondition error instead of parked — holding more bodies
            # would push accounted memory toward the hard limit while
            # consumers drain. Publishes already FIFO-queued behind a held
            # one still park (closing their channel would orphan them).
            self.broker.metrics.flow_publishes_refused += 1
            await self._soft_close_channel(out.channel, ChannelError(
                ErrorCode.PRECONDITION_FAILED,
                "memory overload: broker refusing publishes"))
            return not self.closing
        if ((self._held or self.broker.blocked or self._throttled)
                and self._should_hold(out)):
            self._hold_command(out)
            return True
        try:
            if not self._try_fast_publish(out):
                await self._dispatch(out)
        except HardError as exc:
            await self._hard_close(
                exc.code, exc.text, exc.class_id, exc.method_id)
            return False
        except ChannelError as exc:
            await self._soft_close_channel(out.channel, exc)
        except BrokerError as exc:
            if exc.code.is_hard_error:
                await self._hard_close(
                    exc.code, exc.text,
                    out.method.CLASS_ID, out.method.METHOD_ID)
                return False
            await self._soft_close_channel(
                out.channel,
                ChannelError(exc.code, exc.text,
                             out.method.CLASS_ID, out.method.METHOD_ID))
        return not self.closing

    async def _consume_feed(self, items) -> bool:
        for item in items:
            if isinstance(item, FrameError):
                await self._hard_close(item.code, item.message)
                return False
            if item.type == FrameType.HEARTBEAT:
                continue  # _last_recv already updated
            out = self._assembler.feed_one(item)
            if out is None:
                continue  # content still assembling
            if isinstance(out, FrameError):
                await self._hard_close(out.code, out.message)
                return False
            if not await self._run_command(out):
                return False
        return True

    async def _consume_scan(self, batches) -> bool:
        """The native-parser read loop: walk the scan arrays directly. A
        contained Basic.Publish (method+header+body in one batch, plain
        flags) short-circuits through _fused_publish without constructing
        Frame / Method / AMQCommand objects; everything else falls back to
        the Frame path one frame at a time."""
        partials = self._assembler._partial
        for batch in batches:
            if isinstance(batch, FrameError):
                await self._hard_close(batch.code, batch.message)
                return False
            raw, n, types, channels, offsets, lengths, pub_mark, body_off, \
                body_len = batch
            i = 0
            while i < n:
                ftype = types[i]
                if ftype == 8:  # heartbeat: _last_recv already updated
                    i += 1
                    continue
                channel_id = channels[i]
                off = offsets[i]
                if (ftype == 1 and self._fast_path
                        and channel_id not in partials
                        and not self._held and not self.broker.blocked
                        and not self._throttled):
                    consumed = 0
                    try:
                        mark = pub_mark[i]
                        if mark:
                            # the native scanner already validated the
                            # complete METHOD/HEADER/BODY publish triple:
                            # no sig compare, no shape walk, one body slice
                            consumed = self._fused_publish_marked(
                                raw, i, mark, channel_id, off, offsets,
                                lengths, body_off, body_len)
                        else:
                            sig = raw[off:off + 4]
                            if (sig == _PUBLISH_SIG and i + 1 < n
                                    and types[i + 1] == 2
                                    and channels[i + 1] == channel_id):
                                consumed = self._fused_publish(
                                    raw, i, n, types, channels, offsets,
                                    lengths)
                            elif sig == _ACK_SIG and lengths[i] == 13:
                                consumed = self._fused_ack(
                                    raw, off, channel_id)
                    except HardError as exc:
                        await self._hard_close(
                            exc.code, exc.text, exc.class_id, exc.method_id)
                        return False
                    except ChannelError as exc:
                        await self._soft_close_channel(channel_id, exc)
                        if self.closing:  # flipped during the await
                            return False
                        i += self._fused_skip
                        continue
                    except BrokerError as exc:
                        if exc.code.is_hard_error:
                            await self._hard_close(exc.code, exc.text, 60, 40)
                            return False
                        await self._soft_close_channel(
                            channel_id,
                            ChannelError(exc.code, exc.text, 60, 40))
                        if self.closing:  # flipped during the await
                            return False
                        i += self._fused_skip
                        continue
                    if consumed:
                        i += consumed
                        continue
                frame = Frame(ftype, channel_id, raw[off:off + lengths[i]])
                i += 1
                out = self._assembler.feed_one(frame)
                if out is None:
                    continue
                if isinstance(out, FrameError):
                    await self._hard_close(out.code, out.message)
                    return False
                # a generic command may publish, mutate topology, or read
                # queue state: deferred publishes must land first
                if self._route_pending:
                    self._flush_route_pending()
                if not await self._run_command(out):
                    return False
        return True

    @property
    def _fast_path(self) -> bool:
        # clustered connections take it too: _fused_publish falls back on
        # a cluster-route-cache miss, and _fused_ack settles through the
        # same channel.ack the generic arm uses (remote settles buffer).
        # ACL write denial routes publishes to the generic path so each
        # raises a proper access-refused channel error.
        return self._opened and not self._closing_channels and self._can_write

    @staticmethod
    def _publish_args(payload: bytes):
        """Decode (exchange, routing_key, exrk_raw) off a Basic.Publish
        method payload through the adaptive args cache. None -> generic
        path (truncated payload, or mandatory/immediate bits that need a
        Return render)."""
        global _publish_cache_strikes
        caching = _publish_cache_strikes < _PUBLISH_CACHE_STRIKES
        if caching:
            args_key = payload[6:]
            cached = _PUBLISH_ARGS_CACHE.get(args_key)
            if cached is not None:
                return cached
        try:
            exchange, routing_key, bits, pos = am.parse_publish_wire(payload)
        except (IndexError, UnicodeDecodeError, am.MethodDecodeError):
            return None  # truncated/bad payload: generic path raises properly
        if bits:
            return None  # mandatory / immediate: generic path renders Returns
        exrk_raw = payload[6:pos]
        if caching:
            if len(_PUBLISH_ARGS_CACHE) >= 1024:
                _PUBLISH_ARGS_CACHE.clear()
                _publish_cache_strikes += 1
            if _publish_cache_strikes < _PUBLISH_CACHE_STRIKES:
                _PUBLISH_ARGS_CACHE[args_key] = (
                    exchange, routing_key, exrk_raw)
        return exchange, routing_key, exrk_raw

    @staticmethod
    def _publish_props(header: bytes) -> Optional[BasicProperties]:
        """Decode BasicProperties off a raw content-header payload through
        the adaptive header cache. None -> generic path (the assembler
        raises the proper SYNTAX_ERROR)."""
        global _header_cache_strikes
        caching = _header_cache_strikes < _PUBLISH_CACHE_STRIKES
        if caching:
            props = _HEADER_CACHE.get(header)
            if props is not None:
                return props
        try:
            _class_id, _size, props = BasicProperties.decode_header(header)
        except Exception:
            return None
        if caching:
            if len(_HEADER_CACHE) >= 1024:
                _HEADER_CACHE.clear()
                _header_cache_strikes += 1
            if _header_cache_strikes < _PUBLISH_CACHE_STRIKES:
                _HEADER_CACHE[header] = props
        return props

    def _fused_publish_marked(
        self, raw, i, mark, channel_id, moff, offsets, lengths, body_off,
        body_len
    ) -> int:
        """Marked fast lane: chana_scan_publish already proved frames
        i..i+mark-1 form a complete plain Basic.Publish triple on one
        channel, so this skips the signature compare, the shape checks,
        and the body-gather walk — one slice per wire field. Cache hits
        (the steady-state flow: same exchange+rk, same header shape) are
        checked inline to skip the decode-helper calls entirely. Returns
        the frames consumed or 0 to fall back (TX channel, unknown
        channel, over the size cap, clustered route-cache miss)."""
        payload = raw[moff:moff + lengths[i]]
        if _publish_cache_strikes < _PUBLISH_CACHE_STRIKES:
            args = _PUBLISH_ARGS_CACHE.get(payload[6:])
        else:
            args = None
        if args is None:
            args = self._publish_args(payload)
            if args is None:
                return 0
        exchange, routing_key, exrk_raw = args
        channel = self.channels.get(channel_id)
        if channel is None:
            return 0  # full path raises the proper channel error
        if channel.mode is ChannelMode.TX:
            return 0  # transactional publish: generic path buffers it
        hoff = offsets[i + 1]
        header = raw[hoff:hoff + lengths[i + 1]]
        blen = body_len[i]
        max_body = self._assembler.max_body_size
        if max_body and blen > max_body:
            return 0  # over the message-size cap: the assembler raises 501
        if blen:
            boff = body_off[i]
            body = raw[boff:boff + blen]
        else:
            body = b""
        if _header_cache_strikes < _PUBLISH_CACHE_STRIKES:
            props = _HEADER_CACHE.get(header)
        else:
            props = None
        if props is None:
            props = self._publish_props(header)
            if props is None:
                return 0
        return self._publish_fused_tail(
            channel, channel_id, exchange, routing_key, props, body,
            header, exrk_raw, mark)

    def _fused_publish(
        self, raw, i, n, types, channels, offsets, lengths
    ) -> int:
        """Publish straight off the scan arrays: returns the number of
        frames consumed (method + header + body frames), or 0 to fall back
        to the generic Frame/assembler path (rare shapes: mandatory or
        immediate bits, body spanning into the next read, interleaved
        channels, unknown channel). Semantics mirror _try_fast_publish —
        same publish_sync call, same confirm arming — minus the Return
        cases, which the bit check routes to the fallback. The common
        single-body-frame shape never lands here anymore — chana_scan_publish
        marks it and _fused_publish_marked takes it; this path keeps the
        multi-body-frame (within one read batch) publishes fused."""
        moff = offsets[i]
        args = self._publish_args(raw[moff:moff + lengths[i]])
        if args is None:
            return 0
        exchange, routing_key, exrk_raw = args
        channel = self.channels.get(channels[i])
        if channel is None:
            return 0  # full path raises the proper channel error
        if channel.mode is ChannelMode.TX:
            return 0  # transactional publish: generic path buffers it
        hoff = offsets[i + 1]
        header = raw[hoff:hoff + lengths[i + 1]]
        body_size = int.from_bytes(header[4:12], "big")
        max_body = self._assembler.max_body_size
        if max_body and body_size > max_body:
            return 0  # over the message-size cap: the assembler raises 501
        channel_id = channels[i]
        consumed = 2
        if body_size == 0:
            body = b""
        else:
            j = i + 2
            got = 0
            first = None
            chunks = None
            while got < body_size:
                if j >= n or types[j] != 3 or channels[j] != channel_id:
                    return 0  # spans the batch / interleaved: generic path
                boff = offsets[j]
                blen = lengths[j]
                got += blen
                if got > body_size:
                    return 0  # overflow: generic path raises FRAME_ERROR
                if first is None:
                    first = raw[boff:boff + blen]
                else:
                    if chunks is None:
                        chunks = [first]
                    chunks.append(raw[boff:boff + blen])
                j += 1
            body = first if chunks is None else b"".join(chunks)
            consumed = j - i
        props = self._publish_props(header)
        if props is None:
            return 0
        return self._publish_fused_tail(
            channel, channel_id, exchange, routing_key, props, body,
            header, exrk_raw, consumed)

    def _publish_fused_tail(
        self, channel, channel_id, exchange, routing_key, props, body,
        header, exrk_raw, consumed
    ) -> int:
        """Shared back half of the fused publish lanes: tenant spend,
        router deferral / publish_sync / clustered fast push, confirm
        arming — identical semantics to the pre-split _fused_publish."""
        # count the skip before publish: the except handlers in
        # _consume_scan resume past this publish's frames on soft errors
        self._fused_skip = consumed
        rated = self._tenant_rated
        if rated is not None:
            # tenant publish-rate token spend (same cost formula as
            # _held_cost); may close the tenant gate, which the scan-loop
            # gate check observes before the NEXT frame
            rated.spend(len(body) + self.HELD_COMMAND_OVERHEAD)
        broker = self.broker
        if broker.cluster is None:
            router = broker.router
            if router is not None and router.defer_ok(
                    self.vhost_name, exchange):
                # batch routing: buffer the decoded publish; the whole
                # read batch routes in one kernel call at the next flush
                # point. Confirm arming is identical to the inline path —
                # the confirm can only be RELEASED after a barrier, and
                # every barrier flushes this buffer first.
                seq = self._arm_confirm(channel)
                self._route_pending.append((
                    exchange, routing_key, props, body, header, exrk_raw,
                    seq is not None))
                if seq is not None:
                    self._pending_confirms[channel_id] = seq
                    broker.metrics.confirmed_msgs += 1
                return consumed
            if self._route_pending:
                # non-deferrable publish while deferred ones are buffered:
                # flush first (per-channel/per-queue FIFO)
                self._flush_route_pending()
            seq = self._arm_confirm(channel)
            broker.publish_sync(
                self.vhost_name, exchange, routing_key, props, body,
                header_raw=header,
                marks=self._confirm_marks if seq is not None else None,
                exrk_raw=exrk_raw,
            )
        else:
            # clustered: fused only on a route-cache hit (checked before
            # arming the confirm, so a miss has no side effects) — the
            # generic path resolves the route once and fills the cache
            if not broker.cluster_route_cached(
                    self.vhost_name, exchange, routing_key):
                return 0
            seq = self._arm_confirm(channel)
            pending = self._remote_pending
            buffered_before = len(pending)
            broker.publish_clustered_fast(
                self.vhost_name, exchange, routing_key, props, body,
                header,
                self._confirm_marks if seq is not None else None,
                pending)
            if seq is not None and len(pending) > buffered_before:
                self._remote_strict = True
        if seq is not None:
            # coalesce: one Basic.Ack(multiple=true) per read batch
            self._pending_confirms[channel_id] = seq
            self.broker.metrics.confirmed_msgs += 1
        return consumed

    def _flush_route_pending(self) -> None:
        """Route + publish the deferred fused publishes, in arrival order,
        through one batched router call. Synchronous: the single-node
        publish path never awaits, so a flush can run at any point of
        read-batch processing without yielding the event loop (which is
        exactly what makes deferral invisible to other connections)."""
        entries, self._route_pending = self._route_pending, []
        self.broker.flush_deferred_publishes(
            self.vhost_name, entries, self._confirm_marks)

    async def _batch_barrier(self) -> None:
        """Per-read-batch barrier. When ONLY pipelined remote pushes gate
        this batch's confirms (no local store marks, no sync replication),
        the round trip is offloaded to an ordered background chain and the
        read loop keeps parsing the next batch — read batches pipeline
        through the data plane's per-stream windows instead of stalling
        the whole connection one RTT each. Anything needing the store or
        replication barrier takes the synchronous path below."""
        cluster = self.broker.cluster
        if (self._remote_pending and not self._confirm_marks
                and not self._remote_failures
                and (cluster.replication is None
                     or not cluster.replication.sync)):
            records, self._remote_pending = self._remote_pending, []
            strict, self._remote_strict = self._remote_strict, False
            confirms, self._pending_confirms = self._pending_confirms, {}
            # submit NOW (sync): the RPCs hit the wire while this batch's
            # barrier rides the background chain — successive read batches
            # keep the per-stream in-flight windows full instead of
            # alternating parse / round-trip
            futures = cluster.submit_batch(records)
            prev = self._remote_chain
            self._remote_chain = asyncio.get_event_loop().create_task(
                self._remote_confirm_chain(prev, futures, strict, confirms))
            return
        await self._confirm_barrier()
        self._flush_confirms()

    async def _remote_confirm_chain(
        self, prev: Optional[asyncio.Task], futures: set, strict: bool,
        confirms: dict,
    ) -> None:
        """One offloaded batch: await the previous batch (confirm order —
        a later multiple=true ack would cover an earlier batch's seqs),
        barrier on the already-submitted pushes, then release this batch's
        confirms. A strict failure kills the connection like a failed
        store barrier would — never a false confirm."""
        if prev is not None:
            await prev
        try:
            failures = await self.broker.cluster.await_batch(futures)
        except Exception as exc:  # pragma: no cover - await_batch collects
            failures = [exc]
        if failures:
            if strict:
                log.warning(
                    "remote push failed under confirm barrier: %r; "
                    "dropping connection %d", failures[0], self.id)
                for failure in failures:
                    self._remote_failures.append((failure, False))
                try:
                    self.writer.transport.abort()
                except Exception:
                    pass
                return
            for failure in failures:
                log.warning("remote push failed (best-effort publish): %r",
                            failure)
        if self.closing:
            return
        for channel_id, max_seq in confirms.items():
            if channel_id in self.channels:
                self.send_method(channel_id, am.Basic.Ack(
                    delivery_tag=max_seq, multiple=True))

    async def _confirm_barrier(self) -> None:
        """Durability barrier before releasing publisher confirms: a confirm
        may only reach the client once (a) every pipelined remote queue.push
        of this batch has been accepted by its owner and (b) the store has
        committed every write the confirmed publishes enqueued (message
        blob + queue-log rows — all in one group-commit batch). Free for
        single-node transient traffic: with no remote pushes and no enqueue
        windows recorded, flush([]) resolves immediately."""
        if self._route_pending:
            # deferred publishes must enqueue their store writes (and
            # record their marks) before the marks are consumed below
            self._flush_route_pending()
        await self._settle_remote_failures()
        if self._pending_confirms:
            intervals, self._confirm_marks = self._confirm_marks, []
            await self.broker.store.flush(intervals)
            cluster = self.broker.cluster
            if (cluster is not None and cluster.replication is not None
                    and cluster.replication.sync):
                # chana.mq.replicate.sync: confirms additionally gate on
                # follower acks, so a confirmed persistent message survives
                # the loss of this whole node (bounded by ack-timeout)
                await cluster.replication.sync_barrier()

    async def _settle_remote_failures(self) -> None:
        """Drain pipelined remote pushes and account for their failures:
        a failure covering a confirm-armed (or tx-commit) publish escalates
        — never acknowledge over a lost remote push; best-effort failures
        just log (shared by the confirm barrier and tx.commit)."""
        if self._remote_pending or self._remote_chain is not None:
            await self._drain_remote()
        if self._remote_failures:
            failures, self._remote_failures = self._remote_failures, []
            strict = next((f for f, s in failures if s), None)
            if strict is not None:
                # never confirm over a lost confirm-armed remote push:
                # drop the connection like a failed store barrier would
                raise RuntimeError(
                    f"remote push failed under confirm barrier: "
                    f"{strict!r}") from strict
            for failure, _ in failures:
                log.warning("remote push failed (best-effort publish): %r",
                            failure)

    async def _drain_remote(self) -> None:
        """Flush buffered remote push records through the data plane,
        awaited to completion — including any offloaded batches still in
        the background chain (in-channel ordering: a basic.get right after
        a publish must see the publish applied on the owner). Failures
        collect for the barrier, tagged with whether a confirm-armed
        publish was in the drained batch (strictness is per-drain: a
        batched RPC can't attribute a failure to individual records)."""
        chain = self._remote_chain
        if chain is not None:
            try:
                await chain
            finally:
                if self._remote_chain is chain:
                    self._remote_chain = None
        records, self._remote_pending = self._remote_pending, []
        strict, self._remote_strict = self._remote_strict, False
        if not records:
            return
        for failure in await self.broker.cluster.push_batch(records):
            self._remote_failures.append((failure, strict))

    def _flush_confirms(self) -> None:
        if not self._pending_confirms:
            return
        for channel_id, max_seq in self._pending_confirms.items():
            if channel_id in self.channels:
                self.send_method(
                    channel_id, am.Basic.Ack(delivery_tag=max_seq, multiple=True))
        self._pending_confirms.clear()

    # ------------------------------------------------------------------
    # teardown / close
    # ------------------------------------------------------------------

    async def _hard_close(
        self, code: ErrorCode, text: str, class_id: int = 0, method_id: int = 0
    ) -> None:
        await self._confirm_barrier()
        self._flush_confirms()
        if not self.closing:
            self.send_method(0, am.Connection.Close(
                reply_code=int(code), reply_text=text[:255],
                class_id=class_id, method_id=method_id,
            ))
        self.closing = True

    async def _soft_close_channel(self, channel_id: int, exc: ChannelError) -> None:
        """Channel exception: close just the channel (reference behavior for
        404/405/406 soft errors)."""
        await self._confirm_barrier()
        self._flush_confirms()
        self._pending_confirms.pop(channel_id, None)
        channel = self.channels.pop(channel_id, None)
        if channel is not None:
            channel.release_all()
        self._assembler.abort_channel(channel_id)
        self._closing_channels.add(channel_id)
        self.send_method(channel_id, am.Channel.Close(
            reply_code=int(exc.code), reply_text=exc.text[:255],
            class_id=exc.class_id, method_id=exc.method_id,
        ))

    async def close_channel_ack_timeout(self, channel: ServerChannel) -> None:
        """Sweep-detected delivery-ack timeout (chana.mq.consumer.timeout):
        close just the channel — release_all requeues its unacked — with
        the PRECONDITION_FAILED the RabbitMQ consumer_timeout uses."""
        if (self.closing or channel.closed
                or channel.id in self._closing_channels
                or self.channels.get(channel.id) is not channel):
            # already closing (a prior sweep tick's task may still be inside
            # the close barrier), or the id was reused by a NEW channel —
            # never double-close or close a stranger
            return
        await self._soft_close_channel(channel.id, ChannelError(
            ErrorCode.PRECONDITION_FAILED,
            "delivery acknowledgement timeout"))

    async def _teardown(self) -> None:
        self.closing = True
        # commands held at the publisher gate die with the connection: none
        # were executed or confirmed, but their bodies were counted against
        # the memory gauge at hold time and must be released
        if self._held:
            for commands in self._held.values():
                for command in commands:
                    self.broker.account_held(-self._held_cost(command))
            self._held.clear()
            self._held_bytes = 0
            self._park_t0 = None
        # buffered/chained pipelined remote pushes: send them (the broker
        # accepted these publishes pre-teardown; dropping them would lose
        # messages) and log any failures best-effort
        if self._remote_pending or self._remote_chain is not None:
            try:
                await self._drain_remote()
            except Exception as exc:  # pragma: no cover - teardown races
                log.warning("remote drain failed during teardown: %r", exc)
        for failure, _ in self._remote_failures:
            log.warning("remote push failed during teardown: %r", failure)
        self._remote_failures.clear()
        # requeue unacked, detach consumers
        for channel in list(self.channels.values()):
            channel.release_all()
        self.channels.clear()
        # exclusive queues die with the connection (reference:
        # FrameStage.scala:144-153)
        for queue_name in list(self.exclusive_queues):
            try:
                vhost = self.broker.vhosts.get(self.vhost_name)
                if vhost and queue_name in vhost.queues:
                    await self.broker._remove_queue(vhost, vhost.queues[queue_name])
            except Exception:
                log.exception("failed deleting exclusive queue %s", queue_name)
        self.exclusive_queues.clear()
        if self._heartbeat_task:
            self._heartbeat_task.cancel()
        # buffered deliveries die with the connection (same as bytes
        # already in _out): drop the records and their dirty registration
        self._egress_pending.clear()
        self._egress_records = 0
        self._egress_bytes = 0
        self.broker.egress_dirty.discard(self)
        if self._writer_task:
            self._out_event.set()
            try:
                await asyncio.wait_for(self._writer_task, timeout=2)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._writer_task.cancel()
        if self._out_pooled:
            # arena slots still riding an unwritten _out (writer died or
            # timed out): return them so the pool doesn't bleed capacity
            enc = self._egress
            for slot in self._out_pooled:
                enc.release(slot)
            self._out_pooled = []
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        tenant = self.tenant
        if tenant is not None:
            # fold the per-connection counters into the tenant so its
            # published/delivered series stay monotonic across churn
            tenant.conns.discard(self)
            tenant.published_folded += self.published_msgs
            tenant.delivered_folded += self.delivered_msgs
            self.tenant = None
            self._tenant_rated = None
        self.broker.metrics.connections_closed += 1
        bus = events.ACTIVE
        if bus is not None and self._opened:
            bus.emit("connection.closed", {
                "connection": self.id, "vhost": self.vhost_name,
                "user": self.username,
            })
        if not self.closed.done():
            self.closed.set_result(None)

    # ------------------------------------------------------------------
    # heartbeats (reference: FrameStage.scala:100-107,845-851)
    # ------------------------------------------------------------------

    async def _heartbeat_loop(self) -> None:
        interval = self.heartbeat_s
        try:
            while not self.closing:
                await asyncio.sleep(interval / 2)
                now = time.monotonic()
                if now - self._last_send >= interval / 2:
                    self.send_bytes(HEARTBEAT_BYTES)
                if now - self._last_recv > 2 * interval:
                    # no gate exemption: a gated connection keeps being
                    # read (publishes are held, heartbeats refresh the
                    # clock via the bounded read), and a held-cap-full
                    # peer gets only the bounded _park_grace_tick refresh
                    # — so a stale clock here means a genuinely silent
                    # peer, gated or not
                    log.warning("connection %d heartbeat timeout", self.id)
                    self.closing = True
                    self.writer.close()
                    return
        except asyncio.CancelledError:
            pass

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    async def _dispatch(self, command: AMQCommand) -> None:
        method = command.method
        if (self._remote_pending or self._remote_chain is not None) \
                and type(method) is not am.Basic.Publish:
            # any non-publish command may issue an inline remote RPC
            # (basic.get, queue purge/delete/stats, consume) or observe
            # owner-side state: drain the pipelined publishes first —
            # buffered AND chained — so in-channel ordering holds (a get
            # right after a publish must see the publish). Publishes keep
            # buffering — _on_publish handles its own mandatory/immediate
            # drain.
            await self._drain_remote()
        if command.channel in self._closing_channels:
            # discard everything pipelined behind our Channel.Close until the
            # client acknowledges it
            if isinstance(method, (am.Channel.CloseOk, am.Channel.Close)):
                self._closing_channels.discard(command.channel)
                if isinstance(method, am.Channel.Close):
                    self.send_method(command.channel, am.Channel.CloseOk())
            return
        cid = method.CLASS_ID
        if not self._opened and cid != ClassId.CONNECTION:
            raise HardError(
                ErrorCode.COMMAND_INVALID, "connection not open",
                cid, method.METHOD_ID)
        if cid == ClassId.CONNECTION:
            await self._on_connection(command)
        elif cid == ClassId.CHANNEL:
            await self._on_channel(command)
        elif cid == ClassId.EXCHANGE:
            await self._on_exchange(command)
        elif cid == ClassId.QUEUE:
            await self._on_queue(command)
        elif cid == ClassId.BASIC:
            await self._on_basic(command)
        elif cid == ClassId.CONFIRM:
            self._on_confirm(command)
        elif cid == ClassId.TX:
            await self._on_tx(command)
        elif cid == ClassId.ACCESS:
            self.send_method(command.channel, am.Access.RequestOk(ticket=0))
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unsupported class {cid}",
                cid, method.METHOD_ID)

    def _channel(self, command: AMQCommand) -> ServerChannel:
        channel = self.channels.get(command.channel)
        if channel is None:
            raise HardError(
                ErrorCode.CHANNEL_ERROR, f"channel {command.channel} not open",
                command.method.CLASS_ID, command.method.METHOD_ID)
        return channel

    # -- connection class --------------------------------------------------

    async def _on_connection(self, command: AMQCommand) -> None:
        method = command.method
        if isinstance(method, am.Connection.StartOk):
            ok = self._authenticate(method.mechanism, bytes(method.response))
            if not ok:
                raise HardError(ErrorCode.ACCESS_REFUSED, "authentication failed")
            self._authenticated = True
            capabilities = (method.client_properties or {}).get("capabilities")
            if isinstance(capabilities, dict):
                self._supports_blocked = bool(
                    capabilities.get("connection.blocked"))
                self._supports_cancel_notify = bool(
                    capabilities.get("consumer_cancel_notify"))
            self.send_method(0, am.Connection.Tune(
                channel_max=self.cfg_channel_max,
                frame_max=self.cfg_frame_max,
                heartbeat=self.cfg_heartbeat,
            ))
        elif isinstance(method, am.Connection.SecureOk):
            raise HardError(ErrorCode.NOT_IMPLEMENTED, "secure-ok unexpected")
        elif isinstance(method, am.Connection.TuneOk):
            self.channel_max = min(method.channel_max or self.cfg_channel_max,
                                   self.cfg_channel_max)
            client_fm = method.frame_max or self.cfg_frame_max
            self.frame_max = max(FRAME_MIN_SIZE, min(client_fm, self.cfg_frame_max))
            self._parser.frame_max = self.frame_max
            # heartbeat 0 on either side disables heartbeats entirely (a
            # client sending tune-ok heartbeat=0 must not be timed out)
            if method.heartbeat == 0 or self.cfg_heartbeat == 0:
                self.heartbeat_s = 0
            else:
                self.heartbeat_s = min(method.heartbeat, self.cfg_heartbeat)
            self._tuned = True
            if self.heartbeat_s > 0:
                self._heartbeat_task = asyncio.create_task(self._heartbeat_loop())
        elif isinstance(method, am.Connection.Open):
            if not self._tuned:
                raise HardError(ErrorCode.COMMAND_INVALID, "tune-ok required first")
            vhost_name = method.virtual_host or "/"
            registry = self.broker.tenancy
            # tenant users are confined to their tenant's vhosts: the
            # effective allowlist view merges the registry over the
            # server-wide map (built per handshake, so POST /admin/tenants
            # takes effect without a listener restart)
            permissions = (self.permissions if registry is None
                           else registry.auth_permissions(self.permissions))
            # allowlist BEFORE existence: a restricted user must not be
            # able to use the error-code difference as a vhost-name oracle
            if (permissions is not None and self.username is not None):
                allowed = permissions.get(self.username)
                # a user absent from the map is unrestricted (allowlists
                # are opt-in per user)
                if allowed is not None and vhost_name not in allowed:
                    raise HardError(
                        ErrorCode.ACCESS_REFUSED,
                        f"user '{self.username}' may not access "
                        f"vhost '{vhost_name}'",
                        method.CLASS_ID, method.METHOD_ID)
            vhost = self.broker.vhosts.get(vhost_name)
            if vhost is None or not vhost.active:
                raise HardError(
                    ErrorCode.INVALID_PATH, f"no vhost '{vhost_name}'",
                    method.CLASS_ID, method.METHOD_ID)
            if registry is not None:
                refusal = registry.connection_refusal(vhost_name)
                if refusal is not None:
                    raise HardError(
                        ErrorCode.NOT_ALLOWED, refusal,
                        method.CLASS_ID, method.METHOD_ID)
                tenant = registry.by_vhost.get(vhost_name)
                if tenant is not None:
                    self.tenant = tenant
                    tenant.conns.add(self)
                    if tenant.rated:
                        self._tenant_rated = tenant
                    if tenant.gated:
                        self._throttled = True  # join an already-gated tenant
                    (self._can_configure, self._can_write,
                     self._can_read) = tenant.acl_for(
                        self.username, vhost_name)
            self.vhost_name = vhost_name
            self._opened = True
            self.send_method(0, am.Connection.OpenOk())
        elif isinstance(method, am.Connection.Close):
            # confirms for publishes pipelined ahead of the close must still
            # reach the client before close-ok
            await self._confirm_barrier()
            self._flush_confirms()
            self.send_method(0, am.Connection.CloseOk())
            self.closing = True
        elif isinstance(method, am.Connection.CloseOk):
            self.closing = True
        elif isinstance(method, (am.Connection.Blocked, am.Connection.Unblocked)):
            pass  # client-to-server blocked notifications: informational
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    def _authenticate(self, mechanism: str, response: bytes) -> bool:
        """SASL. Without configured users this matches the reference
        (SaslMechanism.scala:6-98 — PLAIN parses user/password but verifies
        nothing; auth unimplemented there, README 'Status'). With
        chana.mq.auth.users configured, PLAIN verifies against the user
        table in constant time and EXTERNAL is refused (EXCEEDS the
        reference). The effective table merges tenant users
        (tenancy/registry.py) over the server-wide map, rebuilt per
        handshake so runtime tenant changes apply immediately."""
        registry = self.broker.tenancy
        users = (self.users if registry is None
                 else registry.auth_users(self.users))
        if mechanism == "PLAIN":
            parts = response.split(b"\x00")
            if len(parts) != 3:
                return False
            if users is None:
                return True
            import hmac

            try:
                user = parts[1].decode("utf-8")
                password = parts[2].decode("utf-8")
            except UnicodeDecodeError:
                return False
            expected = users.get(user)
            # compare even for unknown users so a timing probe can't
            # enumerate the user table
            ok = hmac.compare_digest(
                (expected if expected is not None else "\x00").encode(),
                password.encode())
            if ok and expected is not None:
                self.username = user
                return True
            return False
        if mechanism == "EXTERNAL":
            return users is None
        return False

    # -- channel class -----------------------------------------------------

    async def _on_channel(self, command: AMQCommand) -> None:
        method = command.method
        cid = command.channel
        if isinstance(method, am.Channel.Open):
            if cid == 0 or cid > self.channel_max:
                raise HardError(
                    ErrorCode.CHANNEL_ERROR, f"bad channel id {cid}",
                    method.CLASS_ID, method.METHOD_ID)
            if cid in self.channels:
                raise HardError(
                    ErrorCode.CHANNEL_ERROR, f"channel {cid} already open",
                    method.CLASS_ID, method.METHOD_ID)
            if self.tenant is not None:
                refusal = self.broker.tenancy.channel_refusal(self.tenant)
                if refusal is not None:
                    # connection exception, like RabbitMQ's channel-limit
                    # refusal (530 not-allowed)
                    raise HardError(
                        ErrorCode.NOT_ALLOWED, refusal,
                        method.CLASS_ID, method.METHOD_ID)
            self.channels[cid] = ServerChannel(self, cid)
            self.send_method(cid, am.Channel.OpenOk())
        elif isinstance(method, am.Channel.Flow):
            channel = self._channel(command)
            channel.flow_active = method.active
            self.send_method(cid, am.Channel.FlowOk(active=method.active))
            if method.active:
                for consumer in channel.consumers.values():
                    consumer.queue.schedule_dispatch()
        elif isinstance(method, am.Channel.FlowOk):
            pass
        elif isinstance(method, am.Channel.Close):
            await self._confirm_barrier()
            self._flush_confirms()
            self._pending_confirms.pop(cid, None)
            channel = self.channels.pop(cid, None)
            if channel is not None:
                channel.release_all()
            self._assembler.abort_channel(cid)
            self.send_method(cid, am.Channel.CloseOk())
        elif isinstance(method, am.Channel.CloseOk):
            pass
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    # -- exchange class (reference: FrameStage.scala:967-1029) -------------

    async def _on_exchange(self, command: AMQCommand) -> None:
        method = command.method
        cid = command.channel
        self._channel(command)
        if (not self._can_configure
                and isinstance(method, (am.Exchange.Declare,
                                        am.Exchange.Delete))):
            self._deny_acl("configure", method)
        if isinstance(method, am.Exchange.Declare):
            self.broker_check_name(method.exchange, method)
            await self.broker.declare_exchange(
                self.vhost_name, method.exchange, method.type,
                passive=method.passive, durable=method.durable,
                auto_delete=method.auto_delete, internal=method.internal,
                arguments=method.arguments,
            )
            if not method.nowait:
                self.send_method(cid, am.Exchange.DeclareOk())
        elif isinstance(method, am.Exchange.Delete):
            await self.broker.delete_exchange(
                self.vhost_name, method.exchange, if_unused=method.if_unused)
            if not method.nowait:
                self.send_method(cid, am.Exchange.DeleteOk())
        elif isinstance(method, am.Exchange.Bind):
            # exchange-to-exchange bindings (EXCEEDS the reference, which
            # stubs these with a TODO log, FrameStage.scala:1023-1027)
            await self.broker.bind_exchange(
                self.vhost_name, method.destination, method.source,
                method.routing_key, method.arguments)
            if not method.nowait:
                self.send_method(cid, am.Exchange.BindOk())
        elif isinstance(method, am.Exchange.Unbind):
            await self.broker.unbind_exchange(
                self.vhost_name, method.destination, method.source,
                method.routing_key, method.arguments)
            if not method.nowait:
                self.send_method(cid, am.Exchange.UnbindOk())
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    def broker_check_name(self, name: str, method: am.Method) -> None:
        if len(name) > 255:
            raise ChannelError(
                ErrorCode.PRECONDITION_FAILED, "name too long",
                method.CLASS_ID, method.METHOD_ID)

    # -- queue class (reference: FrameStage.scala:1031-1149) ---------------

    async def _on_queue(self, command: AMQCommand) -> None:
        method = command.method
        cid = command.channel
        self._channel(command)
        if (not self._can_configure
                and isinstance(method, (am.Queue.Declare, am.Queue.Delete))):
            self._deny_acl("configure", method)
        if isinstance(method, am.Queue.Declare):
            name = method.queue
            if not name:
                name = f"tmp.{uuid.uuid4()}"
            self.broker_check_name(name, method)
            cluster = self.broker.cluster
            vhost_obj = self.broker.vhost(self.vhost_name)
            if (cluster is not None and not method.exclusive
                    and name not in vhost_obj.queues  # local (e.g. exclusive) wins
                    and not cluster.owns_queue(self.vhost_name, name)):
                # clustered queue owned elsewhere: proxy to the owner
                if method.passive:
                    if (self.vhost_name, name) not in cluster.queue_metas:
                        raise ChannelError(
                            ErrorCode.NOT_FOUND, f"no queue '{name}'",
                            method.CLASS_ID, method.METHOD_ID)
                    counts = await cluster.remote_stats(self.vhost_name, name)
                else:
                    reply = await cluster.remote_declare(
                        self.vhost_name, name,
                        durable=method.durable, auto_delete=method.auto_delete,
                        arguments=method.arguments)
                    counts = (int(reply["message_count"]),
                              int(reply["consumer_count"]))
                if not method.nowait:
                    self.send_method(cid, am.Queue.DeclareOk(
                        queue=name, message_count=counts[0],
                        consumer_count=counts[1]))
                return
            queue = await self.broker.declare_queue(
                self.vhost_name, name,
                passive=method.passive, durable=method.durable,
                exclusive_owner=self.id if method.exclusive else None,
                auto_delete=method.auto_delete, arguments=method.arguments,
                connection_id=self.id,
            )
            if method.exclusive:
                self.exclusive_queues.add(name)
            if not method.nowait:
                self.send_method(cid, am.Queue.DeclareOk(
                    queue=name,
                    message_count=queue.message_count,
                    consumer_count=queue.consumer_count,
                ))
        elif isinstance(method, am.Queue.Bind):
            await self.broker.bind_queue(
                self.vhost_name, method.queue, method.exchange,
                method.routing_key, method.arguments, connection_id=self.id)
            if not method.nowait:
                self.send_method(cid, am.Queue.BindOk())
        elif isinstance(method, am.Queue.Unbind):
            await self.broker.unbind_queue(
                self.vhost_name, method.queue, method.exchange,
                method.routing_key, method.arguments, connection_id=self.id)
            self.send_method(cid, am.Queue.UnbindOk())
        elif isinstance(method, am.Queue.Purge):
            site, queue = self.broker.queue_site(
                self.vhost_name, method.queue, self.id)
            if site == "local":
                count = queue.purge()
            elif site == "activate":
                activated = await self.broker.activate_queue(
                    self.vhost_name, method.queue)
                count = activated.purge() if activated else 0
            elif site == "remote":
                count = await self.broker.cluster.remote_purge(
                    self.vhost_name, method.queue)
            else:
                raise ChannelError(
                    ErrorCode.NOT_FOUND, f"no queue '{method.queue}'",
                    method.CLASS_ID, method.METHOD_ID)
            if not method.nowait:
                self.send_method(cid, am.Queue.PurgeOk(message_count=count))
        elif isinstance(method, am.Queue.Delete):
            count = await self.broker.delete_queue(
                self.vhost_name, method.queue,
                if_unused=method.if_unused, if_empty=method.if_empty,
                connection_id=self.id)
            self.exclusive_queues.discard(method.queue)
            if not method.nowait:
                self.send_method(cid, am.Queue.DeleteOk(message_count=count))
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    # -- basic class -------------------------------------------------------

    async def _on_basic(self, command: AMQCommand) -> None:
        method = command.method
        cid = command.channel
        channel = self._channel(command)
        if isinstance(method, am.Basic.Publish):
            await self._on_publish(channel, command)
        elif isinstance(method, am.Basic.Qos):
            channel.set_qos(method.prefetch_size, method.prefetch_count, method.global_)
            self.send_method(cid, am.Basic.QosOk())
        elif isinstance(method, am.Basic.Consume):
            await self._on_consume(channel, method)
        elif isinstance(method, am.Basic.Cancel):
            consumer = channel.consumers.pop(method.consumer_tag, None)
            if consumer is not None:
                from ..cluster.node import RemoteQueueRef

                if isinstance(consumer.queue, RemoteQueueRef):
                    await self.broker.cluster.remote_cancel(
                        consumer.queue.vhost, consumer.queue.name, consumer.tag)
                else:
                    auto_deleted = consumer.queue.remove_consumer(consumer)
                    if auto_deleted:
                        self.broker.schedule_queue_delete(
                            self.vhost_name, consumer.queue.name)
            if not method.nowait:
                self.send_method(cid, am.Basic.CancelOk(
                    consumer_tag=method.consumer_tag))
        elif isinstance(method, am.Basic.Get):
            await self._on_get(channel, method)
        elif isinstance(method, am.Basic.Ack):
            deliveries = channel.resolve_tags(method.delivery_tag, method.multiple)
            self._check_settled_tags(channel, method, deliveries)
            if channel.mode is ChannelMode.TX:
                self._tx_stash_settles(channel, "ack", deliveries)
            else:
                for delivery in deliveries:
                    channel.ack(delivery)
        elif isinstance(method, am.Basic.Nack):
            deliveries = channel.resolve_tags(method.delivery_tag, method.multiple)
            self._check_settled_tags(channel, method, deliveries)
            self._settle_negative(channel, deliveries, method.requeue)
        elif isinstance(method, am.Basic.Reject):
            deliveries = channel.resolve_tags(method.delivery_tag, False)
            self._check_settled_tags(channel, method, deliveries, multiple=False)
            self._settle_negative(channel, deliveries, method.requeue)
        elif isinstance(method, (am.Basic.Recover, am.Basic.RecoverAsync)):
            self._on_recover(channel, method.requeue)
            if isinstance(method, am.Basic.Recover):
                self.send_method(cid, am.Basic.RecoverOk())
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    @staticmethod
    def _tx_stash_settles(
        channel: ServerChannel, kind: str, deliveries: list
    ) -> None:
        for delivery in deliveries:
            channel.tx_stash_settle(kind, delivery)

    def _settle_negative(
        self, channel: ServerChannel, deliveries: list, requeue: bool
    ) -> None:
        """Shared nack/reject settle: requeue or drop, buffered on a tx
        channel (the two methods differ only in how tags were resolved)."""
        if channel.mode is ChannelMode.TX:
            self._tx_stash_settles(
                channel, "requeue" if requeue else "drop", deliveries)
        else:
            for delivery in deliveries:
                if requeue:
                    channel.requeue(delivery)
                else:
                    channel.drop(delivery)

    @staticmethod
    def _check_settled_tags(
        channel: ServerChannel, method, deliveries: list,
        multiple: Optional[bool] = None,
    ) -> None:
        """Ack/Nack/Reject tag validation (RabbitMQ contract): an unknown
        tag is a channel PRECONDITION_FAILED, not a silent no-op. With
        multiple=true a tag never issued on this channel (above the
        delivery-tag counter) is equally unknown; a tag inside the issued
        range whose deliveries are already settled is a legal no-op.
        multiple overrides method.multiple for methods without the field
        (Reject)."""
        AMQPConnection._check_settled_raw(
            channel, deliveries, method.delivery_tag,
            method.multiple if multiple is None else multiple,
            method.CLASS_ID, method.METHOD_ID)

    @staticmethod
    def _check_settled_raw(
        channel: ServerChannel, deliveries: list, tag: int, multiple: bool,
        class_id: int, method_id: int,
    ) -> None:
        if deliveries:
            return
        if not multiple or (tag != 0 and not channel.tag_was_issued(tag)):
            raise ChannelError(
                ErrorCode.PRECONDITION_FAILED,
                f"unknown delivery tag {tag}", class_id, method_id)

    def _fused_ack(self, raw, off: int, channel_id: int) -> int:
        """basic.ack straight off the scan arrays (payload is exactly
        class+method+tag8+bits1 = 13 bytes, no content follows): same
        resolve/validate/settle steps as the generic Basic.Ack arm, minus
        the Frame/Method/AMQCommand/coroutine scaffolding. Returns 1 when
        handled, 0 to fall back (unknown channel: the generic path raises
        the proper channel error)."""
        channel = self.channels.get(channel_id)
        if channel is None:
            return 0
        if channel.mode is ChannelMode.TX:
            return 0  # transactional ack: generic path buffers it
        tag = int.from_bytes(raw[off + 4:off + 12], "big")
        multiple = raw[off + 12] & 1 == 1
        self._fused_skip = 1
        deliveries = channel.resolve_tags(tag, multiple)
        self._check_settled_raw(channel, deliveries, tag, multiple, 60, 80)
        for delivery in deliveries:
            channel.ack(delivery)
        return 1

    def _arm_confirm(self, channel: ServerChannel) -> Optional[int]:
        self._has_published = True
        self.published_msgs += 1
        if channel.mode == ChannelMode.CONFIRM:
            channel.publish_seq += 1
            return channel.publish_seq
        return None

    def _publish_aftermath(
        self, channel: ServerChannel, command: AMQCommand,
        props: BasicProperties, routed: bool, deliverable: bool,
        seq: Optional[int],
    ) -> None:
        method = command.method
        if not routed and method.mandatory:
            self.broker.metrics.returned_msgs += 1
            self.send_command(AMQCommand(
                channel.id,
                am.Basic.Return(
                    reply_code=int(ErrorCode.NO_ROUTE), reply_text="NO_ROUTE",
                    exchange=method.exchange, routing_key=method.routing_key),
                props, command.body, header_raw=command.header_raw))
        elif not deliverable and method.immediate:
            self.broker.metrics.returned_msgs += 1
            self.send_command(AMQCommand(
                channel.id,
                am.Basic.Return(
                    reply_code=int(ErrorCode.NO_CONSUMERS), reply_text="NO_CONSUMERS",
                    exchange=method.exchange, routing_key=method.routing_key),
                props, command.body, header_raw=command.header_raw))
        if seq is not None:
            # coalesce: publish seqs are contiguous per channel and commands
            # are processed in order, so one Basic.Ack(multiple=true) with the
            # batch's max seq confirms everything processed this read batch
            # (reference: the run-length logic at FrameStage.scala:571-596)
            self._pending_confirms[channel.id] = seq
            self.broker.metrics.confirmed_msgs += 1

    def _try_fast_publish(self, command: AMQCommand) -> bool:
        """Per-message hot loop: a single-node Basic.Publish involves no
        awaits anywhere (broker.publish's local branch is plain calls), so
        handling it as a plain call skips three coroutine constructions per
        message (_dispatch → _on_basic → _on_publish). Falls back to the
        full async path (returns False) for anything unusual so error
        semantics stay in one place."""
        method = command.method
        if (type(method) is not am.Basic.Publish
                or self.broker.cluster is not None
                or self._closing_channels
                or not self._opened
                or not self._can_write):
            return False
        channel = self.channels.get(command.channel)
        if channel is None:
            return False  # full path raises the proper channel error
        if channel.mode is ChannelMode.TX:
            return False  # transactional publish: _on_publish buffers it
        props = command.properties or BasicProperties()
        self._tenant_spend(len(command.body or b""))
        seq = self._arm_confirm(channel)
        routed, deliverable = self.broker.publish_sync(
            self.vhost_name, method.exchange, method.routing_key,
            props, command.body,
            mandatory=method.mandatory, immediate=method.immediate,
            header_raw=command.header_raw,
            marks=self._confirm_marks if seq is not None else None,
            exrk_raw=method._values.get("exrk_raw"),
        )
        self._publish_aftermath(channel, command, props, routed, deliverable, seq)
        return True

    async def _on_publish(self, channel: ServerChannel, command: AMQCommand) -> None:
        if not self._can_write:
            self._deny_acl("write", command.method)
        if channel.mode is ChannelMode.TX:
            # transactional publish: buffer until tx.commit. The body counts
            # against the broker memory gate while parked (a flood inside a
            # never-committed tx must not be invisible to backpressure).
            self._has_published = True
            channel.tx_ops.append(("publish", command))
            channel.tx_bytes += len(command.body)
            self.broker.account_memory(len(command.body))
            return
        method = command.method
        if (method.mandatory or method.immediate) and (
                self._remote_pending or self._remote_chain is not None):
            # a mandatory/immediate publish awaits its remote push inline:
            # drain the buffered pipeline first so per-queue FIFO holds
            await self._drain_remote()
        props = command.properties or BasicProperties()
        self._tenant_spend(len(command.body or b""))
        seq = self._arm_confirm(channel)
        buffered_before = len(self._remote_pending)
        routed, deliverable = await self.broker.publish(
            self.vhost_name, method.exchange, method.routing_key,
            props, command.body,
            mandatory=method.mandatory, immediate=method.immediate,
            header_raw=command.header_raw,
            marks=self._confirm_marks if seq is not None else None,
            exrk_raw=method._values.get("exrk_raw"),
            pending=self._remote_pending,
        )
        if seq is not None and len(self._remote_pending) > buffered_before:
            self._remote_strict = True
        self._publish_aftermath(channel, command, props, routed, deliverable, seq)

    def _deny_acl(self, perm: str, method: am.Method) -> None:
        """ACL denial -> AMQP access-refused (403, soft): the channel
        closes, the connection survives (RabbitMQ's mapping)."""
        self.broker.metrics.tenancy_acl_denials_total += 1
        raise ChannelError(
            ErrorCode.ACCESS_REFUSED,
            f"ACL: user '{self.username}' lacks {perm} permission on "
            f"vhost '{self.vhost_name}'",
            method.CLASS_ID, method.METHOD_ID)

    async def _on_consume(self, channel: ServerChannel, method: am.Basic.Consume) -> None:
        if not self._can_read:
            self._deny_acl("read", method)
        tag = method.consumer_tag or f"ctag-{self.id}-{channel.id}-{len(channel.consumers) + 1}"
        if tag in channel.consumers:
            raise ChannelError(
                ErrorCode.NOT_ALLOWED, f"consumer tag '{tag}' in use",
                method.CLASS_ID, method.METHOD_ID)
        # validated up front so local and remotely-owned queues agree
        x_priority = (method.arguments or {}).get("x-priority")
        if x_priority is not None and not isinstance(x_priority, int):
            raise ChannelError(
                ErrorCode.PRECONDITION_FAILED, "invalid x-priority",
                method.CLASS_ID, method.METHOD_ID)
        site, queue = self.broker.queue_site(self.vhost_name, method.queue, self.id)
        if site == "activate":
            queue = await self.broker.activate_queue(self.vhost_name, method.queue)
            site = "local" if queue is not None else "none"
        if site == "remote":
            if method.exclusive:
                raise ChannelError(
                    ErrorCode.NOT_IMPLEMENTED,
                    "exclusive consumers on remotely-owned queues",
                    method.CLASS_ID, method.METHOD_ID)
            # credit window: the client's prefetch if it set one, else the
            # cluster's pipelined consume window
            # (chana.mq.cluster.consume-credit)
            prefetch = (channel.prefetch_count_consumer
                        or channel.prefetch_count_global or 0)
            credit = min(prefetch, self.broker.cluster.consume_credit) \
                if prefetch else self.broker.cluster.consume_credit
            await self.broker.cluster.remote_consume(
                channel, self.vhost_name, method.queue, tag,
                method.no_ack, credit, priority=int(x_priority or 0))
            if not method.nowait:
                self.send_method(channel.id, am.Basic.ConsumeOk(consumer_tag=tag))
            return
        if site == "none":
            raise ChannelError(
                ErrorCode.NOT_FOUND, f"no queue '{method.queue}'",
                method.CLASS_ID, method.METHOD_ID)
        if queue.has_exclusive_consumer() or (method.exclusive and queue.consumers):
            raise ChannelError(
                ErrorCode.ACCESS_REFUSED,
                f"queue '{queue.name}' has an exclusive consumer",
                method.CLASS_ID, method.METHOD_ID)
        if queue.is_stream:
            # attach position must be parseable BEFORE ConsumeOk goes out —
            # a post-Ok failure would leave the client believing it is
            # subscribed
            from ..streams import parse_offset_spec, validate_group_args

            try:
                parse_offset_spec(
                    (method.arguments or {}).get("x-stream-offset"))
            except ValueError as exc:
                raise ChannelError(
                    ErrorCode.PRECONDITION_FAILED, str(exc),
                    method.CLASS_ID, method.METHOD_ID) from None
            group_err = validate_group_args(queue, method.arguments)
            if group_err is not None:
                raise ChannelError(
                    ErrorCode.PRECONDITION_FAILED, group_err,
                    method.CLASS_ID, method.METHOD_ID)
        elif (method.arguments or {}).get("x-group") is not None:
            raise ChannelError(
                ErrorCode.PRECONDITION_FAILED,
                "x-group requires a stream queue (x-queue-type: stream)",
                method.CLASS_ID, method.METHOD_ID)
        consumer = Consumer(
            tag, channel, queue, method.no_ack, method.exclusive, method.arguments)
        channel.consumers[tag] = consumer
        if not method.nowait:
            self.send_method(channel.id, am.Basic.ConsumeOk(consumer_tag=tag))
        queue.add_consumer(consumer)

    async def _on_get(self, channel: ServerChannel, method: am.Basic.Get) -> None:
        if not self._can_read:
            self._deny_acl("read", method)
        site, queue = self.broker.queue_site(self.vhost_name, method.queue, self.id)
        if site == "activate":
            queue = await self.broker.activate_queue(self.vhost_name, method.queue)
            site = "local" if queue is not None else "none"
        if site == "remote":
            await self._on_get_remote(channel, method)
            return
        if site == "none":
            raise ChannelError(
                ErrorCode.NOT_FOUND, f"no queue '{method.queue}'",
                method.CLASS_ID, method.METHOD_ID)
        qm = await queue.basic_get()
        if qm is None:
            self.send_method(channel.id, am.Basic.GetEmpty())
            return
        tag = channel.next_delivery_tag()
        msg = qm.message
        self.send_command(AMQCommand(
            channel.id,
            am.Basic.GetOk(
                delivery_tag=tag, redelivered=qm.redelivered,
                exchange=msg.exchange, routing_key=msg.routing_key,
                message_count=queue.message_count),
            msg.properties, msg.body))
        self.delivered_msgs += 1
        self.broker.metrics.delivered(len(msg.body))
        if method.no_ack:
            self.broker.unrefer(msg)
        else:
            from .entities import Delivery

            delivery = Delivery(qm, queue, channel, "", tag, no_ack=False)
            channel.unacked[tag] = delivery
            queue.note_outstanding(delivery)
            if queue.durable and msg.persisted:
                # mirror the consume dispatch path: the unacked message must
                # survive a restart
                self.broker.store.insert_queue_unacks_nowait(
                    queue.vhost, queue.name,
                    [(msg.id, qm.offset, qm.body_size, qm.expire_at_ms)])
                if queue.repl is not None:
                    queue.repl.append("unacks", {"rows": [
                        [msg.id, qm.offset, qm.body_size, qm.expire_at_ms]]})

    async def _on_get_remote(self, channel: ServerChannel, method: am.Basic.Get) -> None:
        """basic.get on a remotely-owned queue: fetch one message over RPC
        and account for it locally like any other unacked delivery."""
        from ..cluster.node import RemoteQueueRef
        from .entities import Delivery, Message, QueuedMessage

        reply = await self.broker.cluster.remote_get(
            self.vhost_name, method.queue, method.no_ack)
        if reply.get("empty"):
            self.send_method(channel.id, am.Basic.GetEmpty())
            return
        _, _, props = BasicProperties.decode_header(bytes(reply["props_raw"]))
        message = Message(
            int(reply["msg_id"]), props, bytes(reply["body"]),
            str(reply["exchange"]), str(reply["routing_key"]))
        qm = QueuedMessage(message, int(reply["offset"]), reply.get("expire_at_ms"))
        qm.redelivered = bool(reply.get("redelivered"))
        tag = channel.next_delivery_tag()
        self.send_command(AMQCommand(
            channel.id,
            am.Basic.GetOk(
                delivery_tag=tag, redelivered=qm.redelivered,
                exchange=message.exchange, routing_key=message.routing_key,
                message_count=int(reply.get("message_count", 0))),
            message.properties, message.body))
        self.delivered_msgs += 1
        self.broker.metrics.delivered(len(message.body))
        if not method.no_ack:
            ref = RemoteQueueRef(self.broker.cluster, self.vhost_name, method.queue)
            channel.unacked[tag] = Delivery(qm, ref, channel, "", tag, no_ack=False)  # type: ignore[arg-type]

    def _on_recover(self, channel: ServerChannel, requeue: bool) -> None:
        """reference: FrameStage.scala:711-776."""
        if requeue:
            # highest tag first -> requeue's appendleft fast path
            for tag in sorted(channel.unacked, reverse=True):
                channel.requeue(channel.unacked[tag])
        else:
            for tag in sorted(channel.unacked):
                channel.redeliver(channel.unacked[tag])

    # -- confirm / tx ------------------------------------------------------

    def _on_confirm(self, command: AMQCommand) -> None:
        method = command.method
        channel = self._channel(command)
        if isinstance(method, am.Confirm.Select):
            if channel.mode == ChannelMode.TX:
                raise ChannelError(
                    ErrorCode.PRECONDITION_FAILED, "channel is transactional",
                    method.CLASS_ID, method.METHOD_ID)
            channel.mode = ChannelMode.CONFIRM
            if not method.nowait:
                self.send_method(command.channel, am.Confirm.SelectOk())
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    async def _on_tx(self, command: AMQCommand) -> None:
        """tx class with real transactional semantics (EXCEEDS the
        reference, which stubs tx.* with TODO logs,
        FrameStage.scala:1261-1272). tx.select flips the channel into
        transactional mode; publishes and ack/nack/reject buffer in order
        until tx.commit replays them behind the same durability barrier
        publisher confirms use, or tx.rollback discards them. Per 0-9-1,
        rollback returns settled-in-tx deliveries to the unacked set
        WITHOUT redelivering — a client wanting redelivery issues
        basic.recover."""
        method = command.method
        channel = self._channel(command)
        cid = command.channel
        if isinstance(method, am.Tx.Select):
            if channel.mode is ChannelMode.CONFIRM:
                # confirm and tx are mutually exclusive (RabbitMQ contract;
                # mirror of the guard in _on_confirm)
                raise ChannelError(
                    ErrorCode.PRECONDITION_FAILED, "channel is in confirm mode",
                    method.CLASS_ID, method.METHOD_ID)
            channel.mode = ChannelMode.TX
            self.send_method(cid, am.Tx.SelectOk())
        elif isinstance(method, am.Tx.Commit):
            self._require_tx(channel, method)
            await self._tx_commit(channel)
            self.send_method(cid, am.Tx.CommitOk())
        elif isinstance(method, am.Tx.Rollback):
            self._require_tx(channel, method)
            n_ops = len(channel.tx_ops)
            channel.tx_rollback()
            self.broker.metrics.semantics_tx_rollbacks += 1
            bus = events.ACTIVE
            if bus is not None:
                bus.emit("tx.rolledback", {
                    "vhost": self.vhost_name, "channel": channel.id,
                    "ops": n_ops,
                }, vhost_name=self.vhost_name)
            self.send_method(cid, am.Tx.RollbackOk())
        else:
            raise HardError(
                ErrorCode.COMMAND_INVALID, f"unexpected {method.NAME}",
                method.CLASS_ID, method.METHOD_ID)

    @staticmethod
    def _require_tx(channel: ServerChannel, method: am.Method) -> None:
        if channel.mode is not ChannelMode.TX:
            raise ChannelError(
                ErrorCode.PRECONDITION_FAILED, "channel is not transactional",
                method.CLASS_ID, method.METHOD_ID)

    async def _tx_commit(self, channel: ServerChannel) -> None:
        """Replay the buffered ops in arrival order. Mandatory/immediate
        Basic.Returns render before Tx.CommitOk (RabbitMQ ordering), and
        CommitOk is only sent after (a) every clustered push the replay
        buffered has been accepted by its owner and (b) the store has
        committed every persistent write the replay enqueued — the same
        promise a publisher confirm makes, per-op mark windows included.

        Single-node on a WalStore, the whole replay runs inside a WAL
        transaction scope: every persistent write the commit enqueues is
        sealed into ONE tx_batch record, so a SIGKILL between Tx.Commit
        receipt and the WAL fsync replays all-or-nothing — a group-commit
        batch of separate records can tear at record granularity and leave
        a durable prefix of the transaction. The replay loop itself never
        suspends on this path (publish() degenerates to publish_sync and
        settles are plain calls), which is what keeps the scope atomic
        with respect to the commit loop and checkpointer."""
        ops, channel.tx_ops = channel.tx_ops, []
        if channel.tx_bytes:
            self.broker.account_memory(-channel.tx_bytes)
            channel.tx_bytes = 0
        prof = profile.ACTIVE
        t_tx = time.perf_counter_ns() if prof is not None else 0
        store = self.broker.store
        scoped = (self.broker.cluster is None
                  and getattr(store, "tx_begin", None) is not None)
        marks: list[tuple[int, int]] = []
        touched: list = []
        federation = self.broker.federation
        staged_federated: list = []
        mark0 = 0
        if scoped:
            mark0 = store.mark()
            store.tx_begin()
        idx = 0
        try:
            while idx < len(ops):
                op = ops[idx]
                if op[0] == "publish":
                    pub = op[1]
                    method = pub.method
                    if ((method.mandatory or method.immediate)
                            and (self._remote_pending
                                 or self._remote_chain is not None)):
                        # same guard as _on_publish: a mandatory/immediate
                        # publish awaits its remote push inline, so drain
                        # the buffered pipeline first to keep per-queue FIFO
                        await self._drain_remote()
                    props = pub.properties or BasicProperties()
                    buffered_before = len(self._remote_pending)
                    routed, deliverable = await self.broker.publish(
                        self.vhost_name, method.exchange, method.routing_key,
                        props, pub.body,
                        mandatory=method.mandatory, immediate=method.immediate,
                        header_raw=pub.header_raw, marks=marks,
                        exrk_raw=method._values.get("exrk_raw"),
                        pending=self._remote_pending)
                    if len(self._remote_pending) > buffered_before:
                        # a commit-replayed push is always strict: a lost
                        # remote push must fail the commit, never be
                        # silently dropped
                        self._remote_strict = True
                    self._publish_aftermath(
                        channel, pub, props, routed, deliverable, None)
                    if federation is not None:
                        # federated Tx: stage the publish for the link
                        # boundary; the whole staging ships as ONE batch
                        # only after this commit succeeds locally
                        staged_federated.append((
                            method.exchange, method.routing_key,
                            pub.header_raw
                            or props.encode_header(len(pub.body)),
                            pub.body))
                else:
                    kind, delivery = op
                    channel.tx_release_held(delivery)
                    before = store.mark()
                    if kind == "ack":
                        channel.ack(delivery)
                    elif kind == "requeue":
                        channel.requeue(delivery)
                    else:
                        channel.drop(delivery)
                    if scoped:
                        # the settle buffered its unack delete / watermark
                        # for the next loop tick — pull it into the open
                        # scope so staged acks commit atomically with the
                        # staged publishes
                        queue = delivery.queue
                        if queue not in touched:
                            touched.append(queue)
                    else:
                        # the settle path never awaits, so this window
                        # covers exactly the deletes this settle enqueued
                        marks.append((before, store.mark()))
                idx += 1
            if scoped:
                for queue in touched:
                    queue.flush_store_buffers()
        except BaseException:
            # partial-commit failure (e.g. a replayed publish hit a deleted
            # exchange): the error closes the channel, but ops not yet
            # applied must not vanish — parked settles return to unacked so
            # the channel teardown requeues their deliveries. The failed op
            # itself is consumed (a raising publish routed nowhere; settles
            # never raise); later publishes drop, matching implicit-rollback
            # semantics. An open WAL scope aborts whole: the client never
            # got CommitOk, so nothing from this transaction may become
            # durable (no partial replay on recovery). Settle bookkeeping
            # still buffered on the queues is NOT pulled in — it flushes
            # on the next loop tick, outside the aborted scope, so applied
            # settles keep their durable records.
            if scoped:
                store.tx_abort()
            channel.tx_restore_settles(ops[idx + 1:])
            raise
        if scoped:
            lsn = store.tx_seal()
            if lsn > mark0:
                marks = [(mark0, lsn)]
        if prof is not None:
            # staged replay, scope open -> sealed; the awaited flush below
            # is group-commit wall time and lands in WAL_COMMIT already
            prof.stage_ns[profile.TX_COMMIT] += time.perf_counter_ns() - t_tx
            prof.stage_calls[profile.TX_COMMIT] += 1
        self.broker.metrics.semantics_tx_commits += 1
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("tx.committed", {
                "vhost": self.vhost_name, "channel": channel.id,
                "ops": len(ops), "atomic": scoped,
            }, vhost_name=self.vhost_name)
        await self._settle_remote_failures()
        await store.flush(marks)
        if federation is not None and staged_federated:
            # the commit is durable locally (the WAL flush above
            # succeeded): only now hand each link its slice as one
            # all-or-nothing batch — staging any earlier could ship a
            # batch the local cluster never durably committed, leaving
            # the clusters diverged with remote-only messages. Links
            # with no matching exchange see nothing; a down link stages
            # and ships after heal.
            federation.stage_tx_batch(self.vhost_name, staged_federated)
