"""The Broker facade: vhosts, entity lifecycle, routing, persistence glue.

Rebuilds the broker-state side of the reference's entity actors and their
store write-through (ExchangeEntity.scala:198-365, QueueEntity.scala:162-487,
MessageEntity.scala:114-198, VhostEntity.scala:20-131) as plain single-loop
state with explicit, strictly-ordered async store writes:

- control mutations (declare/bind/delete) are AWAITED before replying, so a
  positive reply implies durability — unlike the reference's partial-failure
  windows (SURVEY.md §7.3 "failover without message loss");
- hot-path bookkeeping (queue log, watermark, unacks) is fire-and-forget but
  FIFO via the store's single writer thread (store_bg), preserving order.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from typing import Any, Awaitable, Optional

from .. import chaos, events, profile, trace

from ..amqp.constants import ErrorCode, ExchangeType
from ..amqp.properties import BasicProperties
from ..amqp.value_codec import Timestamp
from ..cluster.idgen import IdGenerator
from ..otel.context import stamp_headers
from ..flow import (
    MemoryAccountant,
    STAGE_PAGE,
    STAGE_REFUSE,
    STAGE_THROTTLE,
)
from ..semantics import DelayService, parse_delay, would_create_cycle
from ..store.api import StoredExchange, StoredMessage, StoredQueue, StoreService
from ..store.memory import MemoryStore
from ..streams import VALID_QUEUE_TYPES, StreamQueue
from ..streams.queue import _parse_max_age_ms
from ..utils.metrics import Metrics
from .entities import Exchange, Message, Queue, VHost, now_ms

log = logging.getLogger("chanamq.broker")

DEFAULT_VHOST = "/"


class BrokerError(Exception):
    """Protocol-level error to be reported on the channel or connection."""

    def __init__(self, code: ErrorCode, message: str) -> None:
        super().__init__(message)
        self.code = code
        self.text = message


class Broker:
    """All broker state for one node."""

    # recovery loads queue metadata in chunks of this many rows so a deep
    # durable backlog never materializes all metas in RAM at once
    RECOVER_META_CHUNK = 4096

    def __init__(
        self,
        store: Optional[StoreService] = None,
        node_id: int = 0,
        message_sweep_interval_s: float = 1.0,
        queue_max_resident: int = 16384,
        memory_high_watermark: int = 0,
        memory_low_watermark: Optional[int] = None,
        consumer_timeout_ms: int = 0,
        store_max_bytes: int = 0,
        stream_segment_bytes: int = 1 << 20,
        stream_segment_age_s: float = 10.0,
        stream_cache_segments: int = 4,
        stream_delivery_batch: int = 128,
        flow_high_watermark: Optional[int] = None,
        flow_low_watermark: Optional[int] = None,
        flow_page_watermark: Optional[int] = None,
        flow_cluster_watermark: Optional[int] = None,
        flow_hard_limit: Optional[int] = None,
        flow_refuse_watermark: Optional[int] = None,
        flow_page_resident: int = 256,
        flow_publish_credit: int = 0,
        flow_consumer_buffer: int = 0,
        park_buffer: Optional[int] = None,
        router_enabled: bool = True,
        router_backend: str = "jax",
        router_min_batch: int = 16,
        router_max_wildcards: int = 512,
        router_max_queues: int = 4096,
        router_verify: bool = False,
        semantics_enabled: bool = True,
        delay_tick_ms: int = 50,
        native_egress: bool = True,
        native_pool_buffers: int = 16,
        native_pool_buffer_kb: int = 256,
    ) -> None:
        self.store = store or MemoryStore()
        self.idgen = IdGenerator(node_id)
        self.metrics = Metrics()
        # native batch egress (chana.mq.native.*): the process-wide
        # encoder + buffer-pool singleton, or None when the native
        # pipeline is unavailable / disabled — connections snapshot this
        # at accept time and fall back to per-delivery Python rendering
        # when None
        self.egress_encoder = None
        if native_egress:
            from .. import native_ext
            self.egress_encoder = native_ext.egress_encoder(
                native_pool_buffers, native_pool_buffer_kb)
        # connections holding un-rendered delivery records; queue dispatch
        # flushes them at pass end (inside the dispatch ledger window)
        self.egress_dirty: set = set()
        self.vhosts: dict[str, VHost] = {}
        # set by chanamq_tpu.cluster.node.ClusterNode when clustering is on
        self.cluster = None
        # span attribution for message traces (chanamq_tpu/trace/):
        # ClusterNode.start() overwrites with its host:port name
        self.trace_node = "local"
        # set by chanamq_tpu.models.service.ForecastService when forecasting
        # is on (chana.mq.forecast.enabled); admin serves its snapshot
        self.forecaster = None
        # set by chanamq_tpu.telemetry.service.TelemetryService when
        # per-entity sampling is on (chana.mq.telemetry.enabled)
        self.telemetry = None
        # set by chanamq_tpu.control.ControlService when the predictive
        # control plane is on (chana.mq.control.enabled)
        self.control = None
        # set by chanamq_tpu.profile.enable_from_config when the cost
        # ledger is on (chana.mq.profile.enabled); admin serves its snapshot
        self.profile = None
        # advanced delivery semantics (chanamq_tpu/semantics/): the master
        # switch gates the per-publish x-delay probe and bind-time cycle
        # refusal; self.delay is None when off, so the disabled publish
        # path pays one attribute load
        self.semantics_enabled = semantics_enabled
        self.delay = (
            DelayService(self, tick_ms=delay_tick_ms)
            if semantics_enabled else None)
        # broker-wide entity gauges, maintained incrementally at every queue
        # mutation site (entities.py / streams/queue.py) so a sampler tick is
        # O(1) instead of a walk over every queue in every vhost
        self.queue_depth = 0
        self.queue_unacked = 0
        self.queue_consumers = 0
        # readiness drain: run_node flips this when the shutdown signal
        # lands, so /admin/health reports 503 while listeners wind down
        self.draining = False
        self.message_sweep_interval_s = message_sweep_interval_s
        # per-queue resident watermark: beyond this depth, durable+persistent
        # bodies are paged out to the store (config chana.mq.queue.max-resident,
        # the reference's passivation: MessageEntity.scala:168-198). 0 = off.
        self.queue_max_resident = queue_max_resident or 0
        # total message-body bytes resident in RAM (gauge; see account_memory)
        self.resident_bytes = 0
        # inbound publisher backpressure (reference leaned on akka-streams
        # demand + TCP, SURVEY.md §7.3): above the high watermark the memory
        # gate closes and publishing connections stop reading; it reopens
        # below the low watermark (default 80% of high). 0 disables.
        self.memory_high_watermark = memory_high_watermark or 0
        self.memory_low_watermark = (
            memory_low_watermark if memory_low_watermark is not None
            else int(self.memory_high_watermark * 0.8))
        if (self.memory_high_watermark
                and self.memory_low_watermark >= self.memory_high_watermark):
            # low >= high would make the gate flap on every accounting tick
            log.warning(
                "memory low watermark %d >= high %d; clamping to 80%% of high",
                self.memory_low_watermark, self.memory_high_watermark)
            self.memory_low_watermark = int(self.memory_high_watermark * 0.8)
        # ack timeout (chana.mq.consumer.timeout; RabbitMQ consumer_timeout,
        # default 30min there): a delivery unacked past this closes its
        # channel with PRECONDITION_FAILED and requeues. 0 disables.
        self.consumer_timeout_ms = consumer_timeout_ms or 0
        # store-growth watermark (chana.mq.store.max-bytes): when page-out
        # is absorbing a flood, RAM stays flat but the store grows without
        # bound — this gate blocks publishers on the store's live data size
        # (sampled each sweep tick), reopening below 80% of the cap. 0 = off.
        self.store_max_bytes = store_max_bytes or 0
        self.store_bytes = 0  # last sampled store size (gauge)
        # stream-queue defaults (chana.mq.stream.*): active segments seal at
        # stream_segment_bytes or after stream_segment_age_s of quiet;
        # cache_segments bounds resident sealed blobs per stream;
        # delivery_batch caps records pushed per cursor per dispatch pass
        self.stream_segment_bytes = stream_segment_bytes or (1 << 20)
        self.stream_segment_age_s = stream_segment_age_s
        self.stream_cache_segments = stream_cache_segments
        self.stream_delivery_batch = stream_delivery_batch or 128
        # publish bodies held at the gate across all connections (gauge;
        # bounded by PARK_BUF_MAX per connection x max-connections)
        self.held_bytes = 0
        # overload-protection ladder (chanamq_tpu/flow/): on whenever a
        # flow or memory high watermark is configured. The accountant's
        # stage 2 IS the legacy memory gate (blocked == stage>=2 composed
        # with the store gate); stages 1/3/4 add paging, cluster pushback
        # and publish refusal around it.
        self.flow: Optional[MemoryAccountant] = None
        self.flow_paging = False       # stage >= 1: aggressive page cap live
        self.flow_refusing = False     # stage >= 4: publishes get 406
        self.flow_page_resident = flow_page_resident or 0
        self.flow_page_resident_active = 0  # flow_page_resident while paging
        self.flow_publish_credit = flow_publish_credit or 0
        self.flow_consumer_buffer = flow_consumer_buffer or 0
        # per-connection park-buffer override (0: connection class default)
        self.park_buf_max = park_buffer or 0
        # fired as fn(old_stage, new_stage) after broker-side actuation
        # (connections send channel.flow, the cluster shrinks credit)
        self.flow_stage_listeners: set[Any] = set()
        fhw = flow_high_watermark or self.memory_high_watermark
        if fhw:
            # when the flow watermark is the derived memory watermark, the
            # low watermark must follow it too so stage 2 keeps the exact
            # legacy block/unblock boundaries
            flw = flow_low_watermark
            if flw is None and fhw == self.memory_high_watermark:
                flw = self.memory_low_watermark
            self.flow = MemoryAccountant(
                high_watermark=fhw,
                low_watermark=flw,
                page_watermark=flow_page_watermark,
                cluster_watermark=flow_cluster_watermark,
                hard_limit=flow_hard_limit,
                refuse_watermark=flow_refuse_watermark,
            )
            self.flow.listeners.append(self._on_flow_stage)
        # multi-tenancy registry (chanamq_tpu/tenancy/): None unless
        # chana.mq.tenant.enabled — every enforcement seam is one
        # attribute load + identity check when off
        self.tenancy: Optional[Any] = None
        # cross-cluster federation (chanamq_tpu/federation/): None unless
        # chana.mq.federation.enabled — the seal/commit/DLX/Tx hooks are
        # one attribute load + identity check when off
        self.federation: Optional[Any] = None
        # OTLP span exporter (chanamq_tpu/otel/): None unless
        # chana.mq.otel.enabled — trace completion pays one hook check
        self.otel: Optional[Any] = None
        self.blocked = False
        self.blocked_reason = ""  # wire-visible cause (Connection.Blocked)
        self._mem_over = False    # resident_bytes above the RAM watermark
        self._store_over = False  # store size above the store watermark
        self._memory_gate = asyncio.Event()
        self._memory_gate.set()
        # callbacks fired on block/unblock transitions (connections send
        # Connection.Blocked/Unblocked to capable clients — an extension
        # the reference never implemented, README.md:10-22)
        self.blocked_listeners: set[Any] = set()
        # live AMQPConnections (registered by serve()): the ack-timeout
        # sweep walks their channels' unacked maps — the one place EVERY
        # outstanding delivery appears, local or remotely-owned
        self.connections: set[Any] = set()
        # strong refs to fire-and-forget tasks (event loops hold tasks only
        # weakly; an unreferenced task can be GC'd before it runs)
        self._bg_tasks: set[asyncio.Task] = set()
        self._sweep_task: Optional[asyncio.Task] = None
        self._msg_delete_buf: list[int] = []
        self._started = False
        # publish route cache (SINGLE-NODE publish_sync only; the clustered
        # publish path never consults it): (vhost, exchange, routing-key)
        # -> resolved local Queue list. A flow's route repeats on every
        # message, so the hot loop skips the matcher walk AND the
        # name->Queue resolution; any topology mutation on this node
        # (declare/delete/bind/unbind) clears the cache outright — churn is
        # rare relative to publishes, and clearing frees dead Queue objects
        # immediately. Only plain key-routed single-hop exchanges cache —
        # headers matchers and e2e graphs route on more than the key.
        # High-cardinality keys (per-message-unique topics) would thrash:
        # after _ROUTE_CACHE_STRIKES overflow-clears the cache disables
        # for the broker's lifetime (same adaptive pattern as the
        # connection's publish-args cache).
        self._route_cache: Optional[dict[tuple[str, str, str], list[Queue]]] = {}
        self._route_cache_strikes = 0
        # clustered twin of _route_cache: (vhost, exchange, rk) ->
        # (local Queue objects, [(owner, names, encoded meta head)]).
        # Invalidation additionally hooks cluster metadata/membership
        # mutations (ClusterNode calls invalidate_routes on those).
        self._cluster_route_cache: Optional[
            dict[tuple[str, str, str], tuple[list, list]]] = {}
        self._cluster_route_strikes = 0
        # data-parallel batch router (chana.mq.router.*): the fused publish
        # path defers eligible messages and flushes whole read batches
        # through compiled binding tables (chanamq_tpu/router/). None when
        # disabled — every router seam is a `router is not None` check.
        self.router = None
        if router_enabled:
            from ..router.engine import TensorRouter

            self.router = TensorRouter(
                self, backend=router_backend,
                min_batch=router_min_batch or 16,
                max_wildcards=router_max_wildcards or 512,
                max_queues=router_max_queues or 4096,
                verify=router_verify)

    _ROUTE_CACHE_MAX = 4096
    _ROUTE_CACHE_STRIKES = 4

    def invalidate_routes(self, vhost: Optional[str] = None,
                          exchange: Optional[str] = None) -> None:
        """Topology changed: cached publish routes are stale. Mutation
        sites that know the one exchange affected pass (vhost, exchange)
        so the batch router recompiles only that table; bulk sites
        (recovery, vhost ops, queue deletion — which unbinds across
        exchanges) pass nothing and everything goes dirty. The flat route
        caches always clear outright either way."""
        if self._route_cache:
            self._route_cache.clear()
        if self._cluster_route_cache:
            self._cluster_route_cache.clear()
        if self.cluster is not None:
            self.cluster.resolve_cache.clear()
        if self.router is not None:
            self.router.invalidate(vhost, exchange)

    def flush_deferred_publishes(
        self, vhost_name: str, entries: list,
        confirm_marks: Optional[list],
    ) -> None:
        """Publish one connection's deferred fused-publish buffer: route
        the whole batch through the tensor router, then run the same
        _publish_local the inline path uses, in arrival order. Rows are
        (exchange, routing_key, props, body, header_raw, exrk_raw,
        confirmed). Never raises: defer_ok pre-validated the exchanges and
        nothing can mutate topology between deferral and flush (the
        connection flushes before every await)."""
        routes, t0, t1 = self.router.route_pending(vhost_name, entries)
        metrics = self.metrics
        prof = profile.ACTIVE
        t_enq = time.perf_counter_ns() if prof is not None else 0
        for entry, queues in zip(entries, routes):
            exchange, routing_key, props, body, header, exrk, confirmed = entry
            metrics.published(len(body))
            if trace.ACTIVE is not None:
                tr = trace.ACTIVE.begin_publish(self.trace_node,
                                                props.headers)
                if tr is not None:
                    # the whole flush routed as one kernel call: each
                    # sampled message carries the batch's ROUTE window
                    tr.span(trace.ROUTE, t0, t1, self.trace_node)
            self._publish_local(
                queues, exchange, routing_key, props, body, False,
                header, confirm_marks if confirmed else None, exrk)
        if prof is not None:
            # batch-granular ledger: one accumulate covers the whole flush
            # (route window from the router, enqueue from the loop above),
            # with calls counting messages so ns/calls reads as us/msg
            n = len(entries)
            sns, sc = prof.stage_ns, prof.stage_calls
            sns[profile.ROUTE] += t1 - t0
            sc[profile.ROUTE] += n
            sns[profile.ENQUEUE] += time.perf_counter_ns() - t_enq
            sc[profile.ENQUEUE] += n

    def spawn(self, coro: Awaitable) -> None:
        """Fire-and-forget a coroutine with a strong reference held until
        it finishes (the loop alone keeps only a weak ref)."""
        task = asyncio.get_event_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def account_memory(self, delta: int) -> None:
        """Track resident message-body bytes (passivation drops, hydration
        reloads, publish adds, final unrefer releases) and drive the
        overload ladder — whose throttle stage is the publisher gate —
        off the gauge."""
        self.resident_bytes += delta
        flow = self.flow
        if flow is not None:
            flow.components["bodies"] = self.resident_bytes
            flow.reevaluate()
            return
        # no flow accountant (no watermark configured anywhere): legacy
        # binary-gate bookkeeping, inert unless memory_high_watermark set
        if not self.memory_high_watermark:
            return
        if not self._mem_over and self.resident_bytes > self.memory_high_watermark:
            self._mem_over = True
            self._update_gate()
        elif self._mem_over and self.resident_bytes <= self.memory_low_watermark:
            self._mem_over = False
            self._update_gate()

    def account_held(self, delta: int) -> None:
        """Track publish bodies parked at the gate (connection hold/release/
        teardown). A separate gauge from resident_bytes — holds must never
        feed back into the gate that created them — but a real resident
        cost the flow accountant sums toward the harder stages."""
        self.held_bytes += delta
        flow = self.flow
        if flow is not None:
            flow.components["held"] = self.held_bytes
            flow.reevaluate()

    def _on_flow_stage(self, old: int, new: int) -> None:
        """Broker-side ladder actuation, then fan out to the registered
        connection/cluster listeners."""
        if new > old:
            self.metrics.flow_escalations += 1
        else:
            self.metrics.flow_deescalations += 1
        self.flow_paging = new >= STAGE_PAGE
        self.flow_page_resident_active = (
            self.flow_page_resident if self.flow_paging else 0)
        self.flow_refusing = new >= STAGE_REFUSE
        mem_over = new >= STAGE_THROTTLE
        if mem_over != self._mem_over:
            self._mem_over = mem_over
            self._update_gate()
        for listener in list(self.flow_stage_listeners):
            try:
                listener(old, new)
            except Exception:
                log.exception("flow stage listener failed")
        bus = events.ACTIVE
        if bus is not None:
            flow = self.flow
            bus.emit(f"flow.stage.{new}", {
                "old": old, "new": new,
                "stage": flow.label if flow is not None else str(new),
                "total_bytes": flow.total if flow is not None else 0,
            })

    def _update_gate(self) -> None:
        """Recompute the publisher gate from its component watermarks
        (resident RAM, store size) and fire transitions exactly once."""
        blocked = self._mem_over or self._store_over
        if blocked:
            self.blocked_reason = (
                "memory high watermark" if self._mem_over
                else "store size high watermark")
        if blocked == self.blocked:
            return
        self.blocked = blocked
        if blocked:
            self._memory_gate.clear()
        else:
            self.blocked_reason = ""
            self._memory_gate.set()
        self._notify_blocked(blocked)

    def _notify_blocked(self, blocked: bool) -> None:
        log.warning(
            "publishers %s: resident=%d/%d store=%d/%d",
            "BLOCKED" if blocked else "unblocked",
            self.resident_bytes, self.memory_high_watermark,
            self.store_bytes, self.store_max_bytes)
        for listener in list(self.blocked_listeners):
            try:
                listener(blocked)
            except Exception:
                log.exception("blocked listener failed")

    async def wait_memory_gate(self, timeout: float = 0.25) -> None:
        """One bounded wait for the memory gate. Callers loop on their own
        liveness condition (connection closing, consumer registration) so a
        parked publisher still wakes for shutdown and dead-peer teardown."""
        if not self._memory_gate.is_set():
            try:
                # no shield: cancelling Event.wait() is harmless, and a
                # shielded inner task would leak one pending task per
                # timeout tick for every parked publisher
                await asyncio.wait_for(self._memory_gate.wait(), timeout)
            except asyncio.TimeoutError:
                pass

    def account_message(self, message: Message) -> None:
        """Count a newly resident message body in the RAM gauge."""
        if message.body is not None and not message.accounted:
            self.account_memory(len(message.body))
            message.accounted = True

    def metrics_snapshot(self) -> dict:
        """Metrics counters plus broker-level gauges (the resident-memory
        gauge an operator needs to see passivation/backpressure working)."""
        snap = self.metrics.snapshot()
        snap["resident_bytes"] = self.resident_bytes
        snap["memory_blocked"] = self.blocked
        snap["memory_high_watermark"] = self.memory_high_watermark
        snap["store_bytes"] = self.store_bytes
        snap["store_max_bytes"] = self.store_max_bytes
        snap["held_bytes"] = self.held_bytes
        snap["queue_depth"] = self.queue_depth
        snap["queue_unacked"] = self.queue_unacked
        snap["queue_consumers"] = self.queue_consumers
        if self.flow is not None:
            flow = self.flow
            snap["flow_stage"] = flow.stage
            snap["flow_stage_label"] = flow.label
            snap["flow_stage_floor"] = flow.floor
            snap["flow_total_bytes"] = flow.total
            snap["flow_peak_bytes"] = flow.peak_total
            snap["flow_hard_limit"] = flow.hard_limit
            for name, value in flow.components.items():
                snap[f"flow_bytes_{name}"] = value
        if self.cluster is not None and self.cluster.replication is not None:
            snap["repl_lag_events"] = self.cluster.replication.total_lag()
        if self.telemetry is not None:
            snap.update(self.telemetry.gauges())
        if self.control is not None:
            snap.update(self.control.gauges())
        return snap

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        await self.store.open()
        await self.recover()
        if DEFAULT_VHOST not in self.vhosts:
            await self.create_vhost(DEFAULT_VHOST)
        if self.message_sweep_interval_s > 0:
            self._sweep_task = asyncio.create_task(self._sweep_loop())
        else:
            # these all piggyback on the sweep: without it they are inert —
            # say so instead of silently not protecting
            for knob, active in (
                ("chana.mq.consumer.timeout", self.consumer_timeout_ms),
                ("chana.mq.store.max-bytes", self.store_max_bytes),
            ):
                if active:
                    log.warning(
                        "%s is set but the sweep is disabled "
                        "(chana.mq.message.sweep-interval <= 0): it will "
                        "NOT be enforced", knob)
        self._started = True

    async def stop(self) -> None:
        if self._sweep_task:
            self._sweep_task.cancel()
            self._sweep_task = None
        self._flush_msg_deletes()
        # paged transient blobs are a passivation convenience, not a
        # durability promise: delete them on clean shutdown so they can't
        # accumulate as orphans (crash leftovers do linger, matching the
        # reference's Cassandra row-TTL story for passivated messages)
        paged_ids: set[int] = set()
        for vhost in self.vhosts.values():
            for queue in vhost.queues.values():
                queue.flush_store_buffers()
                # unacked deliveries hold paged messages too (a delivered-
                # but-unacked transient that was paged before hydration
                # would otherwise leave a permanent orphan blob when stop()
                # is called without connection teardown requeueing it first)
                for qm in itertools.chain(
                        queue.messages,
                        (d.queued for d in queue.outstanding.values())):
                    msg = qm.message
                    if msg.paged and not msg.persisted:
                        msg.paged = False
                        paged_ids.add(msg.id)
        if paged_ids:
            self.store_bg(self.store.delete_messages(list(paged_ids)))
        # let queued background store writes drain before closing
        await self.store.drain_nowait()
        await self.store.close()
        self._started = False

    def store_bg(self, aw: Awaitable[None]) -> None:
        """Fire-and-forget store write. Both built-in backends apply ops
        synchronously at call time (SQLite enqueues into its group-commit
        queue, MemoryStore mutates eagerly), so program order == store
        order; the store's shared tracker keeps the task alive, logs
        failures, and drains at stop()."""
        self.store._fire(aw)

    # -- recovery (reference: stash-until-Loaded preStart reloads,
    #    QueueEntity.scala:107-135, ExchangeEntity.scala:137-174) ----------

    async def recover(self) -> None:
        for name, active in await self.store.all_vhosts():
            vhost = VHost(name)
            vhost.active = active
            self.vhosts[name] = vhost
        for stored_ex in await self.store.all_exchanges():
            vhost = self.vhosts.get(stored_ex.vhost)
            if vhost is None:
                continue
            exchange = Exchange(
                stored_ex.vhost, stored_ex.name, stored_ex.type,
                durable=stored_ex.durable, auto_delete=stored_ex.auto_delete,
                internal=stored_ex.internal, arguments=stored_ex.arguments,
            )
            for routing_key, queue_name, bind_args in stored_ex.binds:
                exchange.matcher.bind(routing_key, queue_name, bind_args)
            for routing_key, dest_name, bind_args in stored_ex.ex_binds:
                exchange.ensure_ex_matcher().bind(
                    routing_key, dest_name, bind_args)
            vhost.exchanges[stored_ex.name] = exchange
        for sq in await self.store.all_queues():
            vhost = self.vhosts.get(sq.vhost)
            if vhost is None:
                continue
            vhost.queues[sq.name] = await self._load_stored_queue(sq)
        n_q = sum(len(v.queues) for v in self.vhosts.values())
        self.invalidate_routes()
        if n_q:
            log.info("recovered %d vhosts, %d queues", len(self.vhosts), n_q)

    async def _load_stored_queue(self, sq: StoredQueue) -> Queue:
        """Reconstruct one queue (pending + unacked messages) from the store
        (reference: stash-until-Loaded preStart reload, QueueEntity.scala:107-135)."""
        if sq.arguments.get("x-queue-type") == "stream":
            return await self._load_stored_stream(sq)
        queue = Queue(
            self, sq.vhost, sq.name, durable=sq.durable,
            auto_delete=sq.auto_delete, ttl_ms=sq.ttl_ms,
            arguments=sq.arguments,
        )
        queue.last_consumed = sq.last_consumed
        # pending messages + unacked (unacked become redeliverable:
        # reference re-reads queue_unacks into the pending set on reload)
        entries = list(sq.msgs) + [
            (offset, msg_id, size, exp)
            for msg_id, (offset, size, exp) in sq.unacks.items()
        ]
        entries.sort(key=lambda e: e[0])
        from .entities import QueuedMessage

        # recovery honors the passivation watermark: metadata (props header,
        # routing, refcount) loads CHUNKED so the transient meta dict never
        # double-holds the whole backlog alongside the inflated messages
        # (the reference streams per-entity, selectQueue on activation) —
        # and bodies load only for the resident head (select_message_metas
        # skips the body column)
        watermark = (queue.max_resident_override
                     if queue.max_resident_override is not None
                     else self.queue_max_resident)
        limit = watermark or len(entries)
        prio_mode = queue.max_priority is not None
        # priority queues: the post-sort head — not the lowest offsets — is
        # what dispatch serves first, so body loading waits until after the
        # sort below; plain queues keep the streaming offset-order load
        resident_ids = (set() if prio_mode
                        else set(m for (_, m, _, _) in entries[:limit]))
        max_offset = sq.last_consumed
        for start in range(0, len(entries), self.RECOVER_META_CHUNK):
            chunk = entries[start:start + self.RECOVER_META_CHUNK]
            metas = await self.store.select_message_metas(
                [msg_id for (_, msg_id, _, _) in chunk])
            bodies = await self.store.select_messages(
                [m for (_, m, _, _) in chunk
                 if m in resident_ids and m in metas])
            for offset, msg_id, size, expire_at in chunk:
                meta = metas.get(msg_id)
                if meta is None:
                    continue
                message = self._inflate(meta)
                message.refer_count = meta.refer_count
                message.persisted = True
                full = bodies.get(msg_id)
                message.body = full.body if full is not None else None
                if full is not None:
                    self.account_message(message)
                qm = QueuedMessage(message, offset, expire_at, body_size=size)
                queue.messages.append(qm)
                if message.body is None:
                    # deep-tail entry recovered without its blob: register
                    # it for batch hydration like a live passivation would
                    queue._passivated.append(qm)
                max_offset = max(max_offset, offset)
        queue.next_offset = max_offset + 1
        if prio_mode:
            # priority queues recover into (priority desc, offset) order;
            # each entry's priority comes from its recovered properties
            for qm in queue.messages:
                qm.priority = min(
                    qm.message.properties.priority or 0, queue.max_priority)
            ordered = sorted(queue.messages,
                             key=lambda q: (-q.priority, q.offset))
            queue.messages.clear()
            queue.messages.extend(ordered)
            # now load bodies for the SORTED head (what dispatch serves
            # first) and rebuild the passivated deque in matching order so
            # hydration batches align with the queue head
            head = ordered[:limit]
            head_bodies = await self.store.select_messages(
                [qm.message.id for qm in head])
            for qm in head:
                sm = head_bodies.get(qm.message.id)
                if sm is not None and qm.message.body is None:
                    qm.message.body = sm.body
                    if qm.message.header_raw is None:
                        qm.message.header_raw = sm.properties_raw
                    self.account_message(qm.message)
            queue._passivated.clear()
            queue._passivated.extend(
                qm for qm in ordered if qm.message.body is None)
        queue.ready_bytes = sum(q.body_size for q in queue.messages)
        # recovery appended to queue.messages directly (bypassing push()),
        # so credit the broker depth gauge in one bulk adjustment; recovered
        # unacks re-entered as ready messages, so no unacked adjustment
        self.queue_depth += len(queue.messages)
        if sq.unacks:
            # Recovered unacks re-enter the queue as ready messages. They
            # must survive a second crash, so convert the store rows:
            # re-insert queue_msgs, rewind the persisted watermark, then
            # drop the unack rows (FIFO store thread preserves order).
            min_unacked = min(off for (off, _, _) in sq.unacks.values())
            queue.last_consumed = min(sq.last_consumed, min_unacked - 1)
            for msg_id, (offset, size, exp) in sq.unacks.items():
                self.store_bg(self.store.insert_queue_msg(
                    sq.vhost, sq.name, offset, msg_id, size, exp))
            self.store_bg(self.store.update_queue_last_consumed(
                sq.vhost, sq.name, queue.last_consumed))
            self.store_bg(self.store.delete_queue_unacks(
                sq.vhost, sq.name, list(sq.unacks)))
        return queue

    async def _load_stored_stream(self, sq: StoredQueue) -> StreamQueue:
        """Reconstruct a stream queue: the sealed-segment index rebuilds
        from metadata only (blobs hydrate lazily when a cursor reads into
        them) and committed cursor offsets reload so reconnecting
        consumers resume where they acked."""
        queue = StreamQueue(
            self, sq.vhost, sq.name, durable=sq.durable,
            arguments=sq.arguments)
        queue.restore_segments(
            await self.store.stream_segment_metas(sq.vhost, sq.name))
        queue.committed = await self.store.select_stream_cursors(
            sq.vhost, sq.name)
        return queue

    async def activate_queue(self, vhost_name: str, name: str) -> Optional[Queue]:
        """Return the local queue, activating it from the shared store or
        replicated metadata if needed (cluster failover: the new owner
        materializes the queue on first touch, SURVEY.md §3.6)."""
        vhost = self.vhosts.get(vhost_name)
        if vhost is None:
            return None
        queue = vhost.queues.get(name)
        if queue is not None:
            return queue
        if self.cluster is not None and self.cluster.replication is not None:
            # a failover promotion may be materializing this queue from a
            # warm replica right now — racing it with the cold path below
            # would claim an empty shell over the promoted copy
            await self.cluster.replication.await_promotion(vhost_name, name)
            queue = vhost.queues.get(name)
            if queue is not None:
                return queue
        stored = await self.store.select_queue(vhost_name, name)
        if stored is not None:
            queue = await self._load_stored_queue(stored)
            # re-check: another task may have activated concurrently
            if name in vhost.queues:
                return vhost.queues[name]
            vhost.queues[name] = queue
            self.invalidate_routes()
            if self.cluster is not None:
                self.cluster.claim_queue(queue)
            return queue
        if self.cluster is not None:
            meta = self.cluster.queue_metas.get((vhost_name, name))
            if meta is not None:
                # transient clustered queue: recreate the shell (contents died
                # with the old owner, matching the reference's HA contract)
                queue = Queue(
                    self, vhost_name, name,
                    durable=bool(meta.get("durable")),
                    auto_delete=bool(meta.get("auto_delete")),
                    ttl_ms=meta.get("ttl_ms"),
                    arguments=dict(meta.get("arguments") or {}),
                )
                vhost.queues[name] = queue
                self.invalidate_routes()
                self.cluster.claim_queue(queue)
                return queue
        return None

    def _inflate(self, stored: StoredMessage) -> Message:
        _, _, props = BasicProperties.decode_header(stored.properties_raw)
        return Message(
            stored.id, props, stored.body, stored.exchange,
            stored.routing_key, stored.ttl_ms,
            header_raw=stored.properties_raw,
        )

    # -- vhosts ------------------------------------------------------------

    def vhost(self, name: str) -> VHost:
        vhost = self.vhosts.get(name)
        if vhost is None or not vhost.active:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no vhost '{name}'")
        return vhost

    async def create_vhost(self, name: str) -> VHost:
        vhost = self.vhosts.get(name)
        if vhost is None:
            vhost = VHost(name)
            self.vhosts[name] = vhost
            self.invalidate_routes()
            await self.store.insert_vhost(name, True)
            if self.cluster is not None:
                self.cluster.broadcast_bg(
                    "meta.apply", {"kind": "vhost.created", "vhost": name})
            fh = events.FIREHOSE
            if fh is not None:
                fh.refresh()  # a firehose targeting this vhost can now tap
        return vhost

    async def delete_vhost(self, name: str) -> bool:
        vhost = self.vhosts.pop(name, None)
        if vhost is None:
            return False
        self.invalidate_routes()
        for queue in list(vhost.queues.values()):
            queue.deleted = True
            queue.gauges_detach()
        await self.store.delete_vhost(name)
        if self.cluster is not None:
            self.cluster.broadcast_bg(
                "meta.apply", {"kind": "vhost.deleted", "vhost": name})
        fh = events.FIREHOSE
        if fh is not None:
            fh.refresh()  # drop the deleted vhost's cached binding table
        return True

    # -- exchanges ---------------------------------------------------------

    async def declare_exchange(
        self, vhost_name: str, name: str, type: str, *,
        passive: bool = False, durable: bool = False, auto_delete: bool = False,
        internal: bool = False, arguments: Optional[dict[str, Any]] = None,
    ) -> Exchange:
        vhost = self.vhost(vhost_name)
        existing = vhost.exchanges.get(name)
        if passive:
            if existing is None:
                raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{name}'")
            return existing
        if name.startswith("amq."):
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, f"exchange name '{name}' is reserved")
        try:
            ex_type = ExchangeType.of(type).value
        except ValueError:
            raise BrokerError(
                ErrorCode.COMMAND_INVALID, f"unknown exchange type '{type}'"
            ) from None
        alt = (arguments or {}).get("alternate-exchange")
        if alt is not None and not isinstance(alt, str):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED, "invalid alternate-exchange")
        if existing is not None:
            if (not existing.equivalent(ex_type, durable, auto_delete, internal)
                    or existing.alternate != alt):
                # alternate-exchange is behavior-bearing: silently ignoring
                # a differing redeclare would let a client believe its AE
                # is active (RabbitMQ: 406 inequivalent arg)
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    f"exchange '{name}' redeclared with different settings")
            return existing
        exchange = Exchange(
            vhost_name, name, ex_type, durable=durable,
            auto_delete=auto_delete, internal=internal, arguments=arguments,
        )
        vhost.exchanges[name] = exchange
        self.invalidate_routes(vhost_name, name)
        if durable:
            await self.store.insert_exchange(StoredExchange(
                vhost=vhost_name, name=name, type=ex_type, durable=durable,
                auto_delete=auto_delete, internal=internal,
                arguments=arguments or {},
            ))
        if self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "exchange.declared", "vhost": vhost_name, "name": name,
                "type": ex_type, "durable": durable,
                "auto_delete": auto_delete, "internal": internal,
                "arguments": arguments or {}, "binds": [],
            })
        return exchange

    async def delete_exchange(
        self, vhost_name: str, name: str, *, if_unused: bool = False
    ) -> None:
        vhost = self.vhost(vhost_name)
        exchange = vhost.exchanges.get(name)
        if exchange is None:
            return  # 0-9-1: deleting a missing exchange is not an error
        if name == "" or name.startswith("amq."):
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, f"exchange '{name}' is reserved")
        if if_unused and not exchange.is_unused():
            raise BrokerError(ErrorCode.PRECONDITION_FAILED, f"exchange '{name}' in use")
        del vhost.exchanges[name]
        self.invalidate_routes(vhost_name, name)
        # e2e bindings die with the exchange on BOTH sides: its own source
        # matchers go with the object; binds from other exchanges to it are
        # swept here (RabbitMQ parity)
        vhost.drop_exchange_refs(name)
        if exchange.durable:
            await self.store.delete_exchange(vhost_name, name)
        await self.store.delete_exchange_binds_dest(vhost_name, name)
        if self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "exchange.deleted", "vhost": vhost_name, "name": name})

    # -- queues ------------------------------------------------------------

    async def declare_queue(
        self, vhost_name: str, name: str, *,
        passive: bool = False, durable: bool = False, exclusive_owner: Optional[int] = None,
        auto_delete: bool = False, arguments: Optional[dict[str, Any]] = None,
        connection_id: Optional[int] = None,
    ) -> Queue:
        vhost = self.vhost(vhost_name)
        existing = vhost.queues.get(name)
        if (existing is None and self.cluster is not None
                and exclusive_owner is None
                and (vhost_name, name) in self.cluster.queue_metas
                and self.cluster.owns_queue(vhost_name, name)):
            # owned here but not yet materialized (failover / lazy activation)
            existing = await self.activate_queue(vhost_name, name)
        if passive:
            if existing is None:
                raise BrokerError(ErrorCode.NOT_FOUND, f"no queue '{name}'")
            self._check_exclusive(existing, connection_id)
            existing.touch()
            return existing
        if name.startswith("amq."):
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, f"queue name '{name}' is reserved")
        if existing is not None:
            self._check_exclusive(existing, connection_id)
            existing.touch()
            return existing
        if self.tenancy is not None:
            # tenant queue quota, checked only for NEW queues (re-declares
            # and passive declares of existing queues stay free)
            refusal = self.tenancy.queue_refusal(vhost_name)
            if refusal is not None:
                raise BrokerError(ErrorCode.PRECONDITION_FAILED, refusal)
        arguments = arguments or {}
        self._validate_queue_args(arguments)
        ttl_ms = arguments.get("x-message-ttl")
        if arguments.get("x-queue-type") == "stream":
            # streams are durable shared logs by definition (RabbitMQ
            # rejects transient/exclusive/auto-delete stream declares)
            if not durable:
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    "stream queues must be durable")
            if exclusive_owner is not None:
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    "stream queues cannot be exclusive")
            if auto_delete:
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    "stream queues cannot auto-delete")
            queue: Queue = StreamQueue(
                self, vhost_name, name, durable=True, arguments=arguments)
        else:
            queue = Queue(
                self, vhost_name, name, durable=durable,
                exclusive_owner=exclusive_owner, auto_delete=auto_delete,
                ttl_ms=ttl_ms, arguments=arguments,
            )
        vhost.queues[name] = queue
        self.invalidate_routes()
        if durable and not exclusive_owner:
            await self.store.insert_queue_meta(StoredQueue(
                vhost=vhost_name, name=name, durable=durable,
                exclusive=False, auto_delete=auto_delete, ttl_ms=ttl_ms,
                last_consumed=0, arguments=arguments,
            ))
        if self.cluster is not None and exclusive_owner is None:
            self.cluster._register_meta(queue)
            epoch = self.cluster.seat_epoch(vhost_name, name)
            if self.cluster.replication is not None and not queue.is_stream:
                # per-queue replication mirrors the ready deque; stream
                # durability is the segment log itself
                self.cluster.replication.attach(queue)
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "queue.declared", "vhost": vhost_name, "name": name,
                "durable": durable, "auto_delete": auto_delete,
                "ttl_ms": ttl_ms, "arguments": arguments,
                "holder": self.cluster.name, "epoch": epoch,
            })
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("queue.declared", {
                "vhost": vhost_name, "queue": name, "durable": durable,
                "exclusive": exclusive_owner is not None,
                "auto_delete": auto_delete,
            })
        return queue

    def _check_exclusive(self, queue: Queue, connection_id: Optional[int]) -> None:
        if queue.exclusive_owner is not None and queue.exclusive_owner != connection_id:
            raise BrokerError(
                ErrorCode.RESOURCE_LOCKED,
                f"queue '{queue.name}' is exclusive to another connection")

    def get_queue(
        self, vhost_name: str, name: str, connection_id: Optional[int] = None
    ) -> Queue:
        vhost = self.vhost(vhost_name)
        queue = vhost.queues.get(name)
        if queue is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no queue '{name}'")
        self._check_exclusive(queue, connection_id)
        return queue

    def queue_site(
        self, vhost_name: str, name: str, connection_id: Optional[int] = None
    ) -> tuple[str, Optional[Queue]]:
        """Locate a queue: ("local", queue) | ("activate", None) — owned here
        but not yet materialized | ("remote", None) | ("none", None)."""
        vhost = self.vhost(vhost_name)
        queue = vhost.queues.get(name)
        if queue is not None:
            self._check_exclusive(queue, connection_id)
            return ("local", queue)
        if self.cluster is not None and (vhost_name, name) in self.cluster.queue_metas:
            if self.cluster.owns_queue(vhost_name, name):
                return ("activate", None)
            return ("remote", None)
        return ("none", None)

    def _queue_is_durable(self, vhost_name: str, name: str) -> bool:
        vhost = self.vhosts.get(vhost_name)
        if vhost is not None and name in vhost.queues:
            return vhost.queues[name].durable
        if self.cluster is not None:
            meta = self.cluster.queue_metas.get((vhost_name, name))
            if meta is not None:
                return bool(meta.get("durable"))
        return False

    def _require_queue_exists(
        self, vhost_name: str, name: str, connection_id: Optional[int]
    ) -> None:
        site, _ = self.queue_site(vhost_name, name, connection_id)
        if site == "none":
            raise BrokerError(ErrorCode.NOT_FOUND, f"no queue '{name}'")

    @staticmethod
    def _validate_queue_args(arguments: dict[str, Any]) -> None:
        """Queue-argument extensions (beyond the reference's x-message-ttl):
        dead-letter routing, length/byte caps, idle expiry. Invalid values
        fail the declare with PRECONDITION_FAILED, RabbitMQ-style."""
        qtype = arguments.get("x-queue-type")
        if qtype is not None and qtype not in VALID_QUEUE_TYPES:
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                f"invalid x-queue-type '{qtype}' "
                f"(one of {'/'.join(VALID_QUEUE_TYPES)})")
        for arg_name in ("x-message-ttl", "x-max-length", "x-max-length-bytes"):
            v = arguments.get(arg_name)
            if v is not None and (not isinstance(v, int) or v < 0):
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED, f"invalid {arg_name}")
        if qtype == "stream":
            try:
                _parse_max_age_ms(arguments.get("x-max-age"))
            except ValueError as exc:
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED, str(exc)) from None
            seg_bytes = arguments.get("x-stream-max-segment-size-bytes")
            if seg_bytes is not None and (
                    not isinstance(seg_bytes, int)
                    or isinstance(seg_bytes, bool) or seg_bytes <= 0):
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    "invalid x-stream-max-segment-size-bytes")
            for incompatible in ("x-max-priority", "x-message-ttl",
                                 "x-dead-letter-exchange", "x-expires",
                                 "x-single-active-consumer"):
                if arguments.get(incompatible) is not None:
                    raise BrokerError(
                        ErrorCode.PRECONDITION_FAILED,
                        f"{incompatible} cannot combine with "
                        "x-queue-type=stream")
            if arguments.get("x-queue-mode") == "lazy":
                raise BrokerError(
                    ErrorCode.PRECONDITION_FAILED,
                    "x-queue-mode=lazy cannot combine with "
                    "x-queue-type=stream")
        elif arguments.get("x-max-age") is not None:
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "x-max-age requires x-queue-type=stream")
        expires = arguments.get("x-expires")
        if expires is not None and (not isinstance(expires, int) or expires <= 0):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED, "invalid x-expires")
        dlx = arguments.get("x-dead-letter-exchange")
        if dlx is not None and not isinstance(dlx, str):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED, "invalid x-dead-letter-exchange")
        dlx_rk = arguments.get("x-dead-letter-routing-key")
        if dlx_rk is not None and not isinstance(dlx_rk, str):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "invalid x-dead-letter-routing-key")
        if dlx_rk is not None and dlx is None:
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "x-dead-letter-routing-key requires x-dead-letter-exchange")
        overflow = arguments.get("x-overflow")
        if overflow is not None and overflow != "drop-head":
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "only x-overflow=drop-head is supported")
        mode = arguments.get("x-queue-mode")
        if mode is not None and mode not in ("default", "lazy"):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED, "invalid x-queue-mode")
        sac = arguments.get("x-single-active-consumer")
        if sac is not None and not isinstance(sac, bool):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "invalid x-single-active-consumer")
        max_prio = arguments.get("x-max-priority")
        if max_prio is not None and (
                not isinstance(max_prio, int) or not 1 <= max_prio <= 255):
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED, "invalid x-max-priority")
        if max_prio is not None and mode == "lazy":
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                "x-max-priority cannot combine with x-queue-mode=lazy")

    async def bind_queue(
        self, vhost_name: str, queue_name: str, exchange_name: str,
        routing_key: str, arguments: Optional[dict] = None,
        connection_id: Optional[int] = None,
    ) -> None:
        vhost = self.vhost(vhost_name)
        self._require_queue_exists(vhost_name, queue_name, connection_id)
        exchange = vhost.exchanges.get(exchange_name)
        if exchange is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{exchange_name}'")
        if exchange_name == "":
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, "cannot bind to the default exchange")
        if self.tenancy is not None:
            # tenant binding quota, counted live off the matchers
            # (conservative: at the cap even an idempotent re-bind refuses)
            refusal = self.tenancy.binding_refusal(vhost_name)
            if refusal is not None:
                raise BrokerError(ErrorCode.PRECONDITION_FAILED, refusal)
        added = exchange.matcher.bind(routing_key, queue_name, arguments)
        if added:
            self.invalidate_routes(vhost_name, exchange_name)
        if added and exchange.durable and self._queue_is_durable(vhost_name, queue_name):
            await self.store.insert_bind(
                vhost_name, exchange_name, queue_name, routing_key, arguments)
        if added and self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "bind.added", "vhost": vhost_name,
                "exchange": exchange_name, "queue": queue_name,
                "key": routing_key, "args": arguments,
            })

    async def bind_exchange(
        self, vhost_name: str, destination: str, source: str,
        routing_key: str, arguments: Optional[dict] = None,
    ) -> None:
        """Exchange-to-exchange binding (EXCEEDS the reference, which stubs
        Exchange.Bind with a TODO log, FrameStage.scala:1023-1027): messages
        accepted by `source` whose routing key/headers match the binding
        flow on to `destination`, which routes them further. Durable when
        both ends are durable."""
        vhost = self.vhost(vhost_name)
        src = vhost.exchanges.get(source)
        if src is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{source}'")
        dst = vhost.exchanges.get(destination)
        if dst is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{destination}'")
        if source == "" or destination == "":
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, "cannot bind the default exchange")
        if self.semantics_enabled and would_create_cycle(
                vhost, source, destination):
            # bind-time refusal (semantics/graph.py): the runtime walk is
            # cycle-safe, but a cyclic graph blocks router closure
            # flattening and is almost certainly a client bug — refuse at
            # declare time like RabbitMQ does for argument conflicts
            bus = events.ACTIVE
            if bus is not None:
                bus.emit("exchange.cycle_refused", {
                    "vhost": vhost_name, "source": source,
                    "destination": destination, "key": routing_key,
                }, vhost_name=vhost_name)
            raise BrokerError(
                ErrorCode.PRECONDITION_FAILED,
                f"binding exchange '{source}' to '{destination}' "
                "would create a cycle")
        added = src.ensure_ex_matcher().bind(routing_key, destination, arguments)
        if added:
            # an e2e bind turns a cached single-hop route stale AND makes
            # the source uncacheable (ex_matcher now set)
            self.invalidate_routes(vhost_name, source)
        if added and src.durable and dst.durable:
            await self.store.insert_exchange_bind(
                vhost_name, source, destination, routing_key, arguments)
        if added and self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "exbind.added", "vhost": vhost_name,
                "source": source, "destination": destination,
                "key": routing_key, "args": arguments,
            })

    async def unbind_exchange(
        self, vhost_name: str, destination: str, source: str,
        routing_key: str, arguments: Optional[dict] = None,
    ) -> None:
        vhost = self.vhost(vhost_name)
        src = vhost.exchanges.get(source)
        if src is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{source}'")
        removed = (src.ex_matcher is not None
                   and src.ex_matcher.unbind(routing_key, destination, arguments))
        if removed:
            self.invalidate_routes(vhost_name, source)
        if removed and src.durable:
            await self.store.delete_exchange_bind(
                vhost_name, source, destination, routing_key)
        if removed and self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "exbind.removed", "vhost": vhost_name,
                "source": source, "destination": destination,
                "key": routing_key, "args": arguments,
            })
        if removed and src.auto_delete and src.is_unused():
            await self.delete_exchange(vhost_name, source)

    async def unbind_queue(
        self, vhost_name: str, queue_name: str, exchange_name: str,
        routing_key: str, arguments: Optional[dict] = None,
        connection_id: Optional[int] = None,
    ) -> None:
        vhost = self.vhost(vhost_name)
        self._require_queue_exists(vhost_name, queue_name, connection_id)
        exchange = vhost.exchanges.get(exchange_name)
        if exchange is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{exchange_name}'")
        removed = exchange.matcher.unbind(routing_key, queue_name, arguments)
        if removed:
            self.invalidate_routes(vhost_name, exchange_name)
        if removed and exchange.durable:
            await self.store.delete_bind(
                vhost_name, exchange_name, queue_name, routing_key)
        if removed and self.cluster is not None:
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "bind.removed", "vhost": vhost_name,
                "exchange": exchange_name, "queue": queue_name,
                "key": routing_key, "args": arguments,
            })
        if removed and exchange.auto_delete and exchange.is_unused():
            await self.delete_exchange(vhost_name, exchange_name)

    async def delete_queue(
        self, vhost_name: str, name: str, *,
        if_unused: bool = False, if_empty: bool = False,
        connection_id: Optional[int] = None,
    ) -> int:
        vhost = self.vhost(vhost_name)
        queue = vhost.queues.get(name)
        if queue is None and self.cluster is not None \
                and (vhost_name, name) in self.cluster.queue_metas:
            if self.cluster.owns_queue(vhost_name, name):
                queue = await self.activate_queue(vhost_name, name)
            else:
                return await self.cluster.remote_delete(
                    vhost_name, name, if_unused=if_unused, if_empty=if_empty)
        if queue is None:
            return 0
        self._check_exclusive(queue, connection_id)
        if if_unused and queue.consumer_count > 0:
            raise BrokerError(ErrorCode.PRECONDITION_FAILED, f"queue '{name}' in use")
        if if_empty and queue.message_count > 0:
            raise BrokerError(ErrorCode.PRECONDITION_FAILED, f"queue '{name}' not empty")
        return await self._remove_queue(vhost, queue)

    async def _remove_queue(self, vhost: VHost, queue: Queue) -> int:
        queue.deleted = True
        del vhost.queues[queue.name]
        self.invalidate_routes()
        count = (queue.message_count if queue.is_stream
                 else len(queue.messages))
        # drop the queue's contribution to the broker entity gauges before
        # the manual consumer/message teardown below (which bypasses the
        # incremental sites), and stop any post-delete settles double-counting
        queue.gauges_detach()
        # unbind everywhere (reference broadcasts QueueDeleted on pub-sub);
        # auto-delete sources go through delete_exchange so e2e bindings on
        # both sides are swept and the deletion replicates cluster-wide
        for exchange in list(vhost.exchanges.values()):
            if exchange.matcher.unbind_queue(queue.name) and exchange.auto_delete \
                    and exchange.is_unused() and exchange.name:
                await self.delete_exchange(vhost.name, exchange.name)
        for consumer in list(queue.consumers):
            consumer.detach()
            queue.consumers.remove(consumer)
        for qm in queue.messages:
            self.unrefer(qm.message)
        queue.messages.clear()
        if queue.durable:
            await self.store.archive_queue(vhost.name, queue.name)
            await self.store.delete_queue(vhost.name, queue.name)
            await self.store.delete_queue_binds(vhost.name, queue.name)
        if queue.is_stream:
            await self.store.delete_stream_data(vhost.name, queue.name)
        if self.cluster is not None and queue.exclusive_owner is None:
            if self.cluster.replication is not None:
                # final "delete" event tears down follower copies
                self.cluster.replication.detach(
                    vhost.name, queue.name, deleted=True)
            # the reference's QueueDeleted pub-sub broadcast
            self.cluster.queue_metas.pop((vhost.name, queue.name), None)
            self.cluster.broadcast_bg("meta.apply", {
                "kind": "queue.deleted", "vhost": vhost.name, "name": queue.name})
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("queue.deleted", {
                "vhost": vhost.name, "queue": queue.name, "messages": count,
            })
        return count

    def schedule_queue_delete(
        self, vhost_name: str, queue_name: str, *, only_if_idle: bool = False
    ) -> None:
        """Auto-delete path from sync contexts (consumer cancel). With
        only_if_idle (the x-expires sweep), idleness is RE-CHECKED inside
        the task: a consumer attached or a declare/get processed between
        the sweep decision and this task running rescues the queue."""

        async def _delete() -> None:
            try:
                vhost = self.vhosts.get(vhost_name)
                if not vhost or queue_name not in vhost.queues:
                    return
                queue = vhost.queues[queue_name]
                if only_if_idle and (
                    not queue.expires_ms or queue.consumers
                    or now_ms() - queue.last_used < queue.expires_ms
                ):
                    return
                await self._remove_queue(vhost, queue)
            except Exception:
                log.exception("auto-delete of queue %s failed", queue_name)

        self.spawn(_delete())

    # -- dead-lettering (no reference analogue: RabbitMQ-style DLX) --------

    def dead_letter(self, queue: Queue, qm: "QueuedMessage", reason: str) -> None:  # noqa: F821
        """Forward a dead message (expired / rejected / maxlen-overflowed)
        to the queue's x-dead-letter-exchange, stamping the x-death header
        (count per (queue, reason), first-death markers) and clearing the
        per-message expiration so it cannot immediately re-expire in the
        dead-letter queue. Cycle safety: an automatic death (expired /
        maxlen) that has already passed through this queue for the same
        reason drops instead of looping; explicit client rejects may cycle
        (RabbitMQ semantics). A missing DLX target drops the message."""
        msg = qm.message
        props = msg.properties
        headers = dict(props.headers) if props.headers else {}
        raw_deaths = headers.get("x-death")
        deaths = ([dict(d) for d in raw_deaths if isinstance(d, dict)]
                  if isinstance(raw_deaths, list) else [])
        entry = next(
            (d for d in deaths
             if d.get("queue") == queue.name and d.get("reason") == reason),
            None)
        if entry is not None:
            if reason != "rejected" and not any(
                    d.get("reason") == "rejected" for d in deaths):
                # fully-automatic cycle (only expired/maxlen deaths in the
                # history): drop instead of looping forever. A history that
                # contains an explicit reject is a client-driven retry
                # topology (work queue -> TTL retry queue -> work queue)
                # and keeps flowing, per RabbitMQ's cycle rule.
                self.metrics.dlx_cycle_drops += 1
                self.unrefer(msg)
                return
            entry["count"] = int(entry.get("count", 1)) + 1
            # re-stamp on every death (RabbitMQ reports the LATEST death
            # time; retry-backoff consumers read x-death[0]["time"])
            entry["time"] = Timestamp(now_ms() // 1000)
            deaths.remove(entry)
            deaths.insert(0, entry)
        else:
            deaths.insert(0, {
                "queue": queue.name, "reason": reason,
                "exchange": msg.exchange,
                "routing-keys": [msg.routing_key],
                "count": 1,
                # Timestamp subclass -> wire tag 'T', matching RabbitMQ
                "time": Timestamp(now_ms() // 1000),
            })
        headers["x-death"] = deaths
        headers.setdefault("x-first-death-queue", queue.name)
        headers.setdefault("x-first-death-reason", reason)
        headers.setdefault("x-first-death-exchange", msg.exchange)
        new_props = props.copy()
        new_props.headers = headers
        new_props.expiration = None
        routing_key = queue.dlx_rk if queue.dlx_rk is not None else msg.routing_key
        self.metrics.dead_lettered_msgs += 1
        self.metrics.dlx_published += 1
        if reason == "expired":
            self.metrics.dlx_expired += 1
        elif reason == "rejected":
            self.metrics.dlx_rejected += 1
        elif reason == "maxlen":
            self.metrics.dlx_maxlen += 1
        bus = events.ACTIVE
        if bus is not None:
            bus.emit("message.dead_lettered", {
                "vhost": queue.vhost, "queue": queue.name,
                "reason": reason, "exchange": queue.dlx,
                "routing_key": routing_key,
                "count": int(deaths[0].get("count", 1)),
            }, vhost_name=queue.vhost)
        self.spawn(self._dead_letter_publish(
            queue.vhost, queue.dlx, routing_key, new_props, msg))

    async def _dead_letter_publish(
        self, vhost_name: str, exchange: str, routing_key: str,
        props: BasicProperties, msg: Message,
    ) -> None:
        """Deliver one dead-lettered message, hydrating a passivated body
        from the store first. The original reference is released only after
        the read so the blob can't be deleted out from under us."""
        try:
            body = msg.body
            if body is None:
                stored = await self.store.select_messages([msg.id])
                sm = stored.get(msg.id)
                if sm is None:
                    return  # blob already gone: nothing to forward
                body = sm.body
            if self.federation is not None:
                # remote-owner DLX routing: a federated dead-letter
                # exchange receives the copy on the far cluster too —
                # staged before the local publish, which may legitimately
                # NOT_FOUND when the exchange exists only remotely
                self.federation.on_dead_letter(
                    vhost_name, exchange, routing_key,
                    props.encode_header(len(body)), body)
            await self.publish(vhost_name, exchange, routing_key, props, body)
        except BrokerError as exc:
            log.warning("dead-letter publish to '%s' dropped: %s",
                        exchange, exc.text)
        except Exception:
            log.exception("dead-letter publish to '%s' failed", exchange)
        finally:
            self.unrefer(msg)

    # -- publish path (reference: FrameStage.scala:462-607 +
    #    ExchangeEntity.publish ExchangeEntity.scala:287-331) --------------

    async def publish(
        self,
        vhost_name: str,
        exchange_name: str,
        routing_key: str,
        properties: BasicProperties,
        body: bytes,
        *,
        mandatory: bool = False,
        immediate: bool = False,
        header_raw: Optional[bytes] = None,
        marks: Optional[list[tuple[int, int]]] = None,
        exrk_raw: Optional[bytes] = None,
        pending: Optional[list] = None,
    ) -> tuple[bool, bool]:
        """Route one message. Returns (routed, deliverable):
        routed=False    -> mandatory handling applies,
        deliverable=False (with immediate) -> immediate handling applies.
        Durability: persistent writes (message blob + queue-log residency)
        are ENQUEUED in order before return; callers that promise durability
        (publisher confirms, cluster push replies) must await
        ``self.store.flush()`` — the group-commit barrier — before doing so.
        marks, when given, collects the store-op enqueue windows of exactly
        this publish's persistent writes (captured around the synchronous
        enqueue block, so no foreign connection's ops can land inside even
        when the clustered path awaits remote pushes) — pass them to
        ``flush(intervals=...)`` for per-publisher failure attribution.
        pending, when given, pipelines plain clustered publishes: push
        records BUFFER into it (nothing is sent here) and the CALLER's
        batch barrier sends one queue.push_many per owner and awaits it —
        per-read-batch RPC round trips instead of per-message ones.
        mandatory/immediate publishes still await inline because their
        Return semantics need the owner's answer (callers drain the buffer
        first to keep per-queue FIFO)."""
        if self.cluster is None:
            return self.publish_sync(
                vhost_name, exchange_name, routing_key, properties, body,
                mandatory=mandatory, immediate=immediate,
                header_raw=header_raw, marks=marks, exrk_raw=exrk_raw)
        delay = self.delay
        if delay is not None and properties.headers is not None:
            delay_ms = parse_delay(properties.headers)
            if delay_ms is not None:
                # x-delay: park in the timer wheel and re-route at fire
                # time (mandatory/immediate are not honored for delayed
                # publishes — delayed-message-exchange plugin parity)
                delay.park(vhost_name, exchange_name, routing_key,
                           properties, body, delay_ms)
                return (True, True)
        tr = None
        t_route = 0
        if trace.ACTIVE is not None:
            tr = trace.ACTIVE.begin_publish(self.trace_node,
                                            properties.headers)
            if tr is not None:
                t_route = time.perf_counter_ns()
        vhost, queue_names = self._publish_route(
            vhost_name, exchange_name, routing_key, properties)
        self.metrics.published(len(body))
        if tr is not None:
            tr.span(trace.ROUTE, t_route, time.perf_counter_ns(),
                    self.trace_node)
        return await self._publish_clustered(
            vhost, exchange_name, routing_key, properties, body,
            queue_names, mandatory=mandatory, immediate=immediate,
            header_raw=header_raw, marks=marks, pending=pending, tr=tr)

    def publish_sync(
        self,
        vhost_name: str,
        exchange_name: str,
        routing_key: str,
        properties: BasicProperties,
        body: bytes,
        *,
        mandatory: bool = False,
        immediate: bool = False,
        header_raw: Optional[bytes] = None,
        marks: Optional[list[tuple[int, int]]] = None,
        exrk_raw: Optional[bytes] = None,
    ) -> tuple[bool, bool]:
        """publish() for the single-node case: identical semantics (the
        local branch never awaits anything), as a plain call so the
        per-message hot loop skips the coroutine machinery. Callers must
        check ``broker.cluster is None`` first."""
        assert self.cluster is None
        delay = self.delay
        if delay is not None and properties.headers is not None:
            delay_ms = parse_delay(properties.headers)
            if delay_ms is not None:
                delay.park(vhost_name, exchange_name, routing_key,
                           properties, body, delay_ms)
                return (True, True)
        tr = None
        t_route = 0
        if trace.ACTIVE is not None:
            tr = trace.ACTIVE.begin_publish(self.trace_node,
                                            properties.headers)
            if tr is not None:
                t_route = time.perf_counter_ns()
        prof = profile.ACTIVE
        t_prof = time.perf_counter_ns() if prof is not None else 0
        cache = self._route_cache
        if cache is not None:
            key = (vhost_name, exchange_name, routing_key)
            queues = cache.get(key)
            if queues is not None:
                # cache hit: resolved Queue objects, no matcher walk
                self.metrics.published(len(body))
                if tr is not None:
                    tr.span(trace.ROUTE, t_route, time.perf_counter_ns(),
                            self.trace_node)
                if prof is not None:
                    return self._publish_local_profiled(
                        prof, t_prof, queues, exchange_name, routing_key,
                        properties, body, immediate, header_raw, marks,
                        exrk_raw)
                return self._publish_local(
                    queues, exchange_name, routing_key, properties,
                    body, immediate, header_raw, marks, exrk_raw)
        vhost, queue_names = self._publish_route(
            vhost_name, exchange_name, routing_key, properties)
        self.metrics.published(len(body))
        queues = [vhost.queues[qn] for qn in queue_names if qn in vhost.queues]
        if cache is not None:
            exchange = vhost.exchanges.get(exchange_name)
            if exchange_name == "" or (
                exchange is not None
                and exchange.ex_matcher is None
                and exchange.alternate is None
                and exchange.type != "headers"
            ):
                if len(cache) >= self._ROUTE_CACHE_MAX:
                    cache.clear()
                    self._route_cache_strikes += 1
                    if self._route_cache_strikes >= self._ROUTE_CACHE_STRIKES:
                        self._route_cache = None
                if self._route_cache is not None:
                    cache[key] = queues
        if tr is not None:
            tr.span(trace.ROUTE, t_route, time.perf_counter_ns(),
                    self.trace_node)
        if prof is not None:
            return self._publish_local_profiled(
                prof, t_prof, queues, exchange_name, routing_key,
                properties, body, immediate, header_raw, marks, exrk_raw)
        return self._publish_local(
            queues, exchange_name, routing_key, properties,
            body, immediate, header_raw, marks, exrk_raw)

    def _publish_local_profiled(
        self, prof, t0: int, queues, exchange_name, routing_key,
        properties, body, immediate, header_raw, marks, exrk_raw,
    ) -> tuple[bool, bool]:
        """publish_sync tail with the cost ledger armed: t0 (taken before
        the route lookup) to here is ROUTE, the _publish_local call is
        ENQUEUE. Split out so the disabled path pays nothing but the
        ACTIVE check."""
        t1 = time.perf_counter_ns()
        out = self._publish_local(
            queues, exchange_name, routing_key, properties,
            body, immediate, header_raw, marks, exrk_raw)
        sns, sc = prof.stage_ns, prof.stage_calls
        sns[profile.ROUTE] += t1 - t0
        sc[profile.ROUTE] += 1
        sns[profile.ENQUEUE] += time.perf_counter_ns() - t1
        sc[profile.ENQUEUE] += 1
        return out

    def cluster_route_cached(
        self, vhost_name: str, exchange_name: str, routing_key: str,
    ) -> bool:
        """Whether publish_clustered_fast will hit for this route (checked
        before arming a confirm so a miss has zero side effects)."""
        cache = self._cluster_route_cache
        return cache is not None \
            and (vhost_name, exchange_name, routing_key) in cache

    def publish_clustered_fast(
        self, vhost_name: str, exchange_name: str, routing_key: str,
        properties: BasicProperties, body: bytes,
        header_raw: Optional[bytes],
        marks: Optional[list[tuple[int, int]]], pending: list,
    ) -> tuple[bool, bool]:
        """publish() for the clustered pipelined case on a route-cache hit:
        identical semantics to _publish_clustered's pending branch (plain
        publish, no mandatory/immediate), as a plain call — no coroutine,
        no exchange walk, no ring hashing, and the push-record meta head
        comes pre-encoded from the cache. Callers must check
        cluster_route_cached first."""
        local, remote = self._cluster_route_cache[
            (vhost_name, exchange_name, routing_key)]
        delay = self.delay
        if delay is not None and properties.headers is not None:
            delay_ms = parse_delay(properties.headers)
            if delay_ms is not None:
                delay.park(vhost_name, exchange_name, routing_key,
                           properties, body, delay_ms)
                return (True, True)
        self.metrics.published(len(body))
        tr = None
        if trace.ACTIVE is not None:
            tr = trace.ACTIVE.begin_publish(self.trace_node,
                                            properties.headers)
            if tr is not None:
                # the route is a dict hit: charge it as one stamp pair
                t_route = time.perf_counter_ns()
                tr.span(trace.ROUTE, t_route, time.perf_counter_ns(),
                        self.trace_node)
        if not local and not remote:
            return (False, True)
        props_raw = header_raw if header_raw is not None \
            else properties.encode_header(len(body))
        if tr is None:
            for owner, names, head in remote:
                pending.append((owner, (
                    vhost_name, names, exchange_name, routing_key,
                    props_raw, body, head)))
        else:
            # 8th element rides into PeerDataPlane.submit_push as its
            # trace kwarg via submit_batch's *rec unpacking
            for owner, names, head in remote:
                pending.append((owner, (
                    vhost_name, names, exchange_name, routing_key,
                    props_raw, body, head, tr)))
        if local:
            self.push_local(local, properties, body, exchange_name,
                            routing_key, props_raw, marks)
        return (True, True)

    def _publish_route(
        self, vhost_name: str, exchange_name: str, routing_key: str,
        properties: BasicProperties,
    ) -> tuple[VHost, set[str]]:
        vhost = self.vhost(vhost_name)
        exchange = vhost.exchanges.get(exchange_name)
        if exchange is None:
            raise BrokerError(ErrorCode.NOT_FOUND, f"no exchange '{exchange_name}'")
        if exchange.internal:
            raise BrokerError(
                ErrorCode.ACCESS_REFUSED, f"exchange '{exchange_name}' is internal")
        if exchange_name == "":
            # default exchange: implicit binding by queue name; a clustered
            # queue may exist only as replicated metadata on this node
            exists = routing_key in vhost.queues or (
                self.cluster is not None
                and (vhost_name, routing_key) in self.cluster.queue_metas)
            queue_names = {routing_key} if exists else set()
        else:
            cluster = self.cluster
            queue_names = vhost.route(
                exchange_name, routing_key, properties.headers,
                queue_exists=(
                    (lambda rk: (vhost_name, rk) in cluster.queue_metas)
                    if cluster is not None else None))
            assert queue_names is not None
        return vhost, queue_names

    def _publish_local(
        self,
        queues: list[Queue],
        exchange_name: str,
        routing_key: str,
        properties: BasicProperties,
        body: bytes,
        immediate: bool,
        header_raw: Optional[bytes],
        marks: Optional[list[tuple[int, int]]],
        exrk_raw: Optional[bytes] = None,
    ) -> tuple[bool, bool]:
        if not queues:
            return (False, True)
        if immediate and not any(
            any(c.can_take(len(body)) for c in q.consumers) for q in queues
        ):
            return (True, False)
        self.push_local(
            queues, properties, body, exchange_name, routing_key,
            header_raw, marks, exrk_raw)
        return (True, True)

    def push_local(
        self,
        queues: list[Queue],
        properties: BasicProperties,
        body: bytes,
        exchange_name: str,
        routing_key: str,
        header_raw: Optional[bytes],
        marks: Optional[list[tuple[int, int]]],
        exrk_raw: Optional[bytes] = None,
    ) -> Message:
        """The one local persistent-enqueue block, shared by the single-node
        publish, the clustered publish, and the cluster push handler: build
        the Message, decide persistence (reference: ExchangeEntity.scala:302
        — message persistent AND >=1 routed queue durable), enqueue the blob
        (not awaited: the queue-log rows from queue.push() land in the SAME
        group-commit batch, so one commit covers the message and all its
        residencies), push to every queue with body_size computed once
        (fanout passivation safety), and record the attribution window."""
        mark0 = self.store.mark()
        tr = None
        t_enq = 0
        if trace.ACTIVE is not None:
            tr = trace.ACTIVE.current
            if tr is not None:
                t_enq = time.perf_counter_ns()
                if tr.w3c is not None:
                    # propagated context: one copy-on-write header rewrite
                    # here covers EVERY egress of this message — consumer
                    # deliveries, the persisted blob, stream records (and
                    # through them federated FED_SHIP segments), and
                    # staged FED_TX/FED_PUBLISH frames all render from
                    # these properties once header_raw is dropped
                    properties, changed = stamp_headers(properties, tr.w3c)
                    if changed:
                        header_raw = None
                # routing attributes for the trace query layer / OTLP
                # render (sampled messages only; setdefault keeps the
                # origin's routing when a clustered push re-applies)
                tr.attr("vhost", queues[0].vhost)
                tr.attr("exchange", exchange_name)
                tr.attr("routing_key", routing_key)
                tr.attr("queue", ",".join(q.name for q in queues))
                registry = self.tenancy
                if registry is not None:
                    owner = registry.tenant_of_vhost(queues[0].vhost)
                    if owner is not None:
                        tr.attr("tenant", owner)
        message = Message(
            self.idgen.next_id(), properties, body, exchange_name, routing_key,
            properties.expiration_ms(), header_raw=header_raw,
        )
        message.exrk_raw = exrk_raw
        if tr is not None:
            message.trace = tr
        message.refer_count = len(queues)
        self.account_message(message)
        # streams never reference the shared Message after push (the log
        # copies the bytes into its own record), so they neither persist
        # the blob nor may a classic sibling passivate the body before the
        # stream's copy: persistence keys on classic durables only, and
        # streams go FIRST in the fanout
        persist = message.is_persistent and any(
            q.durable and not q.is_stream for q in queues)
        if len(queues) > 1 and any(q.is_stream for q in queues):
            queues = sorted(queues, key=lambda q: not q.is_stream)
        if persist:
            message.persisted = True
            self.store.insert_message_nowait(StoredMessage(
                id=message.id,
                properties_raw=message.header_payload(),
                body=body, exchange=exchange_name, routing_key=routing_key,
                refer_count=len(queues), ttl_ms=message.ttl_ms,
            ))
        body_size = len(body)
        for queue in queues:
            queue.push(message, body_size=body_size)
        if tr is not None:
            tr.span(trace.ENQUEUE, t_enq, time.perf_counter_ns(),
                    self.trace_node)
            if tr.w3c is not None and all(q.is_stream for q in queues):
                # stream records are COPIES of this message: nothing ever
                # delivers/settles this Message object, so the origin half
                # of a forced trace completes at append. The consumer side
                # (local cursor reads, or a federated mirror) continues
                # under the same W3C trace id via the stamped record
                # headers. Seeded traces keep their existing lifecycle.
                trace.ACTIVE.finish(tr)
        if marks is not None:
            mark1 = self.store.mark()
            if mark1 > mark0:
                marks.append((mark0, mark1))
        fh = events.FIREHOSE
        if fh is not None and fh.tap_bindings:
            fh.tap_publish(exchange_name, routing_key, body, queues)
        return message

    async def _publish_clustered(
        self, vhost: VHost, exchange_name: str, routing_key: str,
        properties: BasicProperties, body: bytes, queue_names: set[str],
        *, mandatory: bool, immediate: bool,
        header_raw: Optional[bytes] = None,
        marks: Optional[list[tuple[int, int]]] = None,
        pending: Optional[list] = None,
        tr=None,
    ) -> tuple[bool, bool]:
        """Cluster publish: routing already happened locally on the
        replicated exchange metadata; per-owner queue.push RPCs carry the
        message to remote queue owners (the reference's ExchangeEntity ->
        QueueEntity ask path, ExchangeEntity.scala:287-331, with one hop
        instead of two)."""
        assert self.cluster is not None
        local: list[Queue] = []
        by_owner: dict[str, list[str]] = {}
        for name in queue_names:
            queue = vhost.queues.get(name)
            if queue is not None:
                local.append(queue)
                continue
            if (vhost.name, name) not in self.cluster.queue_metas:
                continue
            if self.cluster.owns_queue(vhost.name, name):
                activated = await self.activate_queue(vhost.name, name)
                if activated is not None:
                    local.append(activated)
            else:
                owner = self.cluster.queue_owner(vhost.name, name)
                by_owner.setdefault(owner, []).append(name)
        cache = self._cluster_route_cache
        if cache is not None and pending is not None \
                and not mandatory and not immediate:
            exchange = vhost.exchanges.get(exchange_name)
            if exchange_name == "" or (
                exchange is not None
                and exchange.ex_matcher is None
                and exchange.alternate is None
                and exchange.type != "headers"
            ):
                from ..cluster.dataplane import encode_push_meta_head
                remote = [
                    (owner, names, encode_push_meta_head(
                        vhost.name, names, exchange_name, routing_key))
                    for owner, names in by_owner.items()]
                if len(cache) >= self._ROUTE_CACHE_MAX:
                    cache.clear()
                    self._cluster_route_strikes += 1
                    if self._cluster_route_strikes >= self._ROUTE_CACHE_STRIKES:
                        self._cluster_route_cache = None
                if self._cluster_route_cache is not None:
                    cache[(vhost.name, exchange_name, routing_key)] = (
                        list(local), remote)
        if not local and not by_owner:
            return (False, True)
        props_raw = header_raw if header_raw is not None \
            else properties.encode_header(len(body))
        had_consumer = any(
            any(c.can_take(len(body)) for c in q.consumers) for q in local
        )
        if immediate:
            # immediate is all-or-none like the single-node path: probe every
            # owner first (no enqueue), then either push everywhere or nowhere
            for owner, names in by_owner.items():
                try:
                    _, owner_had = await self.cluster.remote_push(
                        owner, vhost.name, names, props_raw, body,
                        exchange_name, routing_key, check_consumers=True,
                        check_only=True)
                    had_consumer = had_consumer or owner_had
                except Exception as exc:
                    log.warning("remote consumer probe to %s failed: %r", owner, exc)
            if not had_consumer:
                return (True, False)
        pushed_remote = False
        if pending is not None and not mandatory and not immediate:
            # pipelined: buffer the push record; the caller's batch barrier
            # submits them to the binary data plane and awaits the covering
            # micro-batches — per-batch round trips instead of per-message,
            # and the body bytes ride by reference all the way to the
            # socket. routed is reported optimistically; a failed push
            # surfaces at the barrier (confirm-mode: connection error,
            # never a false confirm; else best-effort, logged)
            for owner, names in by_owner.items():
                if tr is None:
                    pending.append((owner, (
                        vhost.name, names, exchange_name, routing_key,
                        props_raw, body)))
                else:
                    pending.append((owner, (
                        vhost.name, names, exchange_name, routing_key,
                        props_raw, body, None, tr)))
                pushed_remote = True
        else:
            for owner, names in by_owner.items():
                try:
                    pushed, owner_had_consumer = await self.cluster.remote_push(
                        owner, vhost.name, names, props_raw, body,
                        exchange_name, routing_key, check_consumers=False,
                        tr=tr)
                    pushed_remote = pushed_remote or pushed
                    had_consumer = had_consumer or owner_had_consumer
                except Exception as exc:
                    log.warning("remote push to %s failed: %r", owner, exc)
        if not local and not pushed_remote:
            # every target was remote and none accepted: unroutable in effect
            return (False, True)
        if local:
            if tr is not None and trace.ACTIVE is not None:
                # re-pin: awaits above may have run other publishes
                trace.ACTIVE.current = tr
            self.push_local(
                local, properties, body, exchange_name, routing_key,
                props_raw, marks)
        return (True, True)

    # -- message refcounting (reference: MessageEntity.scala:134-166) ------

    def unrefer(self, message: Message) -> None:
        self.unrefer_n(message, 1)

    def unrefer_n(self, message: Message, n: int) -> None:
        message.refer_count -= n
        if message.refer_count <= 0 and message.accounted:
            self.account_memory(-len(message.body or b""))
            message.accounted = False
        if message.refer_count <= 0 and (message.persisted or message.paged):
            message.persisted = False
            message.paged = False
            # coalesce per loop tick: one executemany instead of a store op
            # per message (ids are snowflakes, never reused, so a delayed
            # delete can't clash with a later insert)
            buf = self._msg_delete_buf
            buf.append(message.id)
            if len(buf) == 1:
                asyncio.get_event_loop().call_soon(self._flush_msg_deletes)

    def _flush_msg_deletes(self) -> None:
        ids, self._msg_delete_buf = self._msg_delete_buf, []
        if ids:
            self.store_bg(self.store.delete_messages(ids))

    async def _sample_store_size(self) -> None:
        """One store-size sample for the store-growth gate: over at the
        cap, back under at 80% of it (hysteresis like the RAM gate)."""
        try:
            size = await self.store.approx_data_bytes()
        except Exception:
            log.exception("store size sample failed")
            return
        if size is None:
            return  # backend cannot report; gate inert
        self.store_bytes = size
        if not self._store_over and size > self.store_max_bytes:
            self._store_over = True
            self._update_gate()
        elif self._store_over and size <= int(self.store_max_bytes * 0.8):
            self._store_over = False
            self._update_gate()

    def _flow_tick(self, stream_cache_bytes: int) -> None:
        """One sweep-tick sample of the polled accountant components (WAL
        memtable, data-plane buffers, connection out-buffers, stream sealed
        cache, chaos inflation), then a single ladder reevaluation. The
        hot components (bodies, held) are pushed synchronously elsewhere;
        hooking these cold ones at their mutation sites would tax every
        WAL append and socket write for sweep-tick-freshness data."""
        flow = self.flow
        c = flow.components
        c["stream_cache"] = stream_cache_bytes
        c["wal_memtable"] = int(
            getattr(self.store, "memtable_pending_bytes", 0) or 0)
        c["cluster_inflight"] = (
            self.cluster.dataplane_buffered_bytes()
            if self.cluster is not None else 0)
        out_buffers = 0
        for conn in self.connections:
            out_buffers += len(conn._out)
        c["out_buffers"] = out_buffers
        if chaos.ACTIVE is not None:
            fault = chaos.ACTIVE.decide("flow.tick")
            c["chaos"] = (
                fault.inflate_bytes
                if fault is not None and fault.kind == "pressure" else 0)
        flow.reevaluate()

    # -- TTL sweep ---------------------------------------------------------

    async def _sweep_loop(self) -> None:
        """Periodic head-expiry pass so TTL'd messages don't linger in
        consumerless queues (the reference used per-entity timers,
        MessageEntity.scala:168-198)."""
        try:
            while True:
                await asyncio.sleep(self.message_sweep_interval_s)
                if self.store_max_bytes:
                    await self._sample_store_size()
                now = now_ms()
                expired_queues: list[Queue] = []
                overdue_channels: set = set()
                timeout = self.consumer_timeout_ms
                stream_cache_bytes = 0
                for vhost in self.vhosts.values():
                    for queue in vhost.queues.values():
                        before = len(queue.messages)
                        queue._expire_head()
                        self.metrics.expired_msgs += before - len(queue.messages)
                        if queue.is_stream:
                            stream_cache_bytes += queue.cache_bytes
                        elif self.flow_paging:
                            # stage >= 1: page bodies beyond the pressure
                            # cap out of queues that aren't receiving
                            # pushes (the push path handles active ones)
                            queue.passivate_excess(self.flow_page_resident)
                        # x-expires: the queue itself dies after idling
                        # unused (no consumers, no gets/declares)
                        if (queue.expires_ms and not queue.consumers
                                and now - queue.last_used >= queue.expires_ms):
                            expired_queues.append(queue)
                if self.flow is not None:
                    self._flow_tick(stream_cache_bytes)
                if self.tenancy is not None:
                    # refill tenant token buckets and move memory-share
                    # floors (one pass over the registry per sweep)
                    self.tenancy.tick(self.message_sweep_interval_s or 1.0)
                if timeout:
                    # ack timeout: walk every live connection's channels —
                    # the one registry where every outstanding delivery
                    # appears (local consume/get, remotely-owned queues,
                    # and settles parked in uncommitted transactions)
                    cutoff = now - timeout
                    for conn in list(self.connections):
                        for channel in list(conn.channels.values()):
                            if channel.closed:
                                continue
                            if channel.has_delivery_older_than(cutoff):
                                overdue_channels.add(channel)
                for queue in expired_queues:
                    log.info("queue %s idle-expired (x-expires=%dms)",
                             queue.name, queue.expires_ms)
                    self.schedule_queue_delete(
                        queue.vhost, queue.name, only_if_idle=True)
                for channel in overdue_channels:
                    log.warning(
                        "channel %d: delivery ack timeout (%d ms), closing",
                        channel.id, timeout)
                    self.spawn(
                        channel.connection.close_channel_ack_timeout(channel))
        except asyncio.CancelledError:
            pass
