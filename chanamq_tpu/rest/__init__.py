"""L6: localhost admin REST API."""

from .admin import AdminServer

__all__ = ["AdminServer"]
