"""Exchange-to-exchange binding graph guards.

The runtime publish walk (VHost.route) is cycle-SAFE — its visited set
terminates any loop — but a cyclic topology is still almost certainly a
client bug, and it blocks the TensorRouter from flattening the graph
closure into its compiled tables (a DAG closure is finite; a cyclic one
is not). So with semantics enabled, Exchange.Bind REFUSES a binding
that would close a directed cycle with 406 PRECONDITION_FAILED — the
same fail-at-declare posture RabbitMQ takes for argument conflicts —
and the visited-set walk stays on as defense in depth (pre-existing
durable topologies recovered from the store are not re-validated).

Edges considered are e2e bindings only. Alternate-exchange fallbacks
can also chain, but they fire only for UNROUTED messages, so an
alternate loop self-terminates at the first exchange that routes; the
runtime visited set covers the rest.
"""

from __future__ import annotations

from typing import Iterable


def e2e_destinations(vhost, name: str) -> Iterable[str]:
    """Destination exchange names reachable in ONE e2e hop from `name`."""
    ex = vhost.exchanges.get(name)
    if ex is None or ex.ex_matcher is None:
        return ()
    return {dest for _key, dest, _args in ex.ex_matcher.bindings()}


def would_create_cycle(vhost, source: str, destination: str) -> bool:
    """Whether binding source -> destination closes a directed cycle:
    true iff source is already reachable FROM destination (or the bind
    is a self-loop). Depth-first over the e2e edge set — bind-time cost,
    never on the publish path."""
    if source == destination:
        return True
    seen: set[str] = set()
    stack = [destination]
    while stack:
        name = stack.pop()
        if name == source:
            return True
        if name in seen:
            continue
        seen.add(name)
        stack.extend(e2e_destinations(vhost, name))
    return False
