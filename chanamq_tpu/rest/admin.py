"""Localhost admin REST API.

Capability parity with the reference's AdminApi
(chana-mq-server .../rest/AdminApi.scala:20-61: GET /admin/vhost/put/{v} and
/admin/vhost/delete/{v}, bound to localhost, with access logging), extended
with the observability endpoints the reference lacked (SURVEY.md §5):
metrics snapshot, overview, and per-queue stats.

Hand-rolled HTTP/1.1 on asyncio (no third-party web framework in the image).
Reads are GET with JSON responses (plus the text-format Prometheus scrape at
/metrics); vhost mutations require POST.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional
from urllib.parse import unquote

from ..broker.broker import Broker

log = logging.getLogger("chanamq.admin")


class AdminServer:
    def __init__(
        self, broker: Broker, host: str = "127.0.0.1", port: int = 15672
    ) -> None:
        self.broker = broker
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        log.info("admin API on http://%s:%d/admin", self.host, self.port)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            # drain headers
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            status, payload = await self._route(method, path)
            if isinstance(payload, str):
                # pre-rendered text body (Prometheus exposition format)
                body = payload.encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = json.dumps(payload, default=str).encode()
                ctype = "application/json"
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n".encode() + body
            )
            await writer.drain()
            log.info("%s %s -> %s", method, path, status.split()[0])
        except (asyncio.TimeoutError, ConnectionResetError):
            pass
        except Exception:
            log.exception("admin request failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _route(self, method: str, path: str) -> tuple[str, object]:
        if method not in ("GET", "POST"):
            return "405 Method Not Allowed", {"error": "GET/POST only"}
        segments = [unquote(s) for s in path.strip("/").split("/") if s]
        if segments == ["metrics"] and method == "GET":
            # conventional Prometheus scrape path (text exposition format);
            # errors still produce an HTTP response, not a dropped scrape
            try:
                return "200 OK", self._prometheus()
            except Exception as exc:
                return "500 Internal Server Error", {"error": str(exc)}
        if not segments or segments[0] != "admin":
            return "404 Not Found", {"error": "unknown path"}
        segments = segments[1:]
        try:
            # vhost mutations (paths mirror the reference's AdminApi, but
            # require POST: a GET mutation is CSRF-triggerable from any web
            # page even on localhost)
            if len(segments) == 3 and segments[0] == "vhost" and segments[1] == "put":
                if method != "POST":
                    return "405 Method Not Allowed", {"error": "use POST"}
                await self.broker.create_vhost(segments[2])
                return "200 OK", {"ok": True, "vhost": segments[2]}
            if len(segments) == 3 and segments[0] == "vhost" and segments[1] == "delete":
                if method != "POST":
                    return "405 Method Not Allowed", {"error": "use POST"}
                deleted = await self.broker.delete_vhost(segments[2])
                return "200 OK", {"ok": deleted, "vhost": segments[2]}
            if method != "GET":
                return "405 Method Not Allowed", {"error": "use GET"}
            # observability
            if segments == ["metrics"]:
                return "200 OK", self.broker.metrics_snapshot()
            if segments == ["overview"]:
                return "200 OK", self._overview()
            if len(segments) == 2 and segments[0] == "queues":
                return "200 OK", self._queues(segments[1])
            if len(segments) == 2 and segments[0] == "exchanges":
                return "200 OK", self._exchanges(segments[1])
            if segments == ["cluster"]:
                return "200 OK", self._cluster()
            if segments == ["replication"]:
                return "200 OK", self._replication()
            if segments == ["forecast"]:
                forecaster = getattr(self.broker, "forecaster", None)
                if forecaster is None:
                    return "200 OK", {"enabled": False}
                return "200 OK", forecaster.snapshot()
        except Exception as exc:
            return "500 Internal Server Error", {"error": str(exc)}
        return "404 Not Found", {"error": "unknown path"}

    # metric name -> prometheus type; everything else in the snapshot is a
    # gauge. Latency percentiles are exported as computed gauges (the
    # histogram buckets aren't cumulative-format compatible as stored).
    _PROM_COUNTERS = frozenset({
        "published_msgs", "published_bytes", "delivered_msgs",
        "delivered_bytes", "returned_msgs", "confirmed_msgs",
        "expired_msgs", "dead_lettered_msgs", "connections_opened",
        "connections_closed", "connections_refused",
        "repl_events_shipped", "repl_batches_shipped",
        "repl_events_applied", "repl_resyncs", "repl_promotions",
        "repl_ack_timeouts",
    })

    @staticmethod
    def _prom_label(value: str) -> str:
        return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    def _prometheus(self) -> str:
        """Prometheus text exposition of the broker metrics + per-queue
        gauges (exceeds the reference, which had no metrics at all —
        SURVEY.md §5 'observability': throughput was measured by grepping
        log lines)."""
        out: list[str] = []
        snap = self.broker.metrics_snapshot()
        for key, value in snap.items():
            if isinstance(value, bool):
                value = int(value)  # e.g. memory_blocked -> 0/1 gauge
            if not isinstance(value, (int, float)):
                continue  # None percentiles before any traffic
            kind = "counter" if key in self._PROM_COUNTERS else "gauge"
            out.append(f"# TYPE chanamq_{key} {kind}")
            out.append(f"chanamq_{key} {value}")
        out.append("# TYPE chanamq_queue_messages gauge")
        out.append("# TYPE chanamq_queue_ready_bytes gauge")
        out.append("# TYPE chanamq_queue_unacked gauge")
        out.append("# TYPE chanamq_queue_consumers gauge")
        for vhost in self.broker.vhosts.values():
            vl = self._prom_label(vhost.name)
            for queue in vhost.queues.values():
                labels = f'{{vhost="{vl}",queue="{self._prom_label(queue.name)}"}}'
                out.append(
                    f"chanamq_queue_messages{labels} {queue.message_count}")
                out.append(
                    f"chanamq_queue_ready_bytes{labels} {queue.ready_bytes}")
                out.append(
                    f"chanamq_queue_unacked{labels} {len(queue.outstanding)}")
                out.append(
                    f"chanamq_queue_consumers{labels} {queue.consumer_count}")
        forecaster = getattr(self.broker, "forecaster", None)
        if forecaster is not None and forecaster.forecast is not None:
            # next-tick telemetry forecast (models/service.py): one gauge
            # per feature, in the telemetry ring's units
            out.append("# TYPE chanamq_forecast gauge")
            for name, value in forecaster.forecast.items():
                out.append(
                    f'chanamq_forecast{{feature="{self._prom_label(name)}"}}'
                    f" {value}")
            if forecaster.loss is not None:
                out.append("# TYPE chanamq_forecast_loss gauge")
                out.append(f"chanamq_forecast_loss {forecaster.loss}")
        return "\n".join(out) + "\n"

    def _overview(self) -> dict:
        return {
            "product": "chanamq-tpu",
            "vhosts": {
                name: {
                    "active": vhost.active,
                    "exchanges": len(vhost.exchanges),
                    "queues": len(vhost.queues),
                    "messages": sum(len(q.messages) for q in vhost.queues.values()),
                    "consumers": sum(q.consumer_count for q in vhost.queues.values()),
                }
                for name, vhost in self.broker.vhosts.items()
            },
            "metrics": self.broker.metrics_snapshot(),
        }

    def _queues(self, vhost_name: str) -> list:
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return []
        return [
            {
                "name": queue.name,
                "durable": queue.durable,
                "exclusive": queue.exclusive_owner is not None,
                "auto_delete": queue.auto_delete,
                "messages": queue.message_count,
                "ready_bytes": queue.ready_bytes,
                "unacked": len(queue.outstanding),
                "consumers": queue.consumer_count,
                "ttl_ms": queue.ttl_ms,
                "arguments": queue.arguments or {},
            }
            for queue in vhost.queues.values()
        ]

    def _cluster(self) -> dict:
        """Cluster membership + queue ownership as the operator sees it
        (exceeds the reference, whose admin surface was vhost-only)."""
        cluster = self.broker.cluster
        if cluster is None or cluster.membership is None:
            # membership is None until ClusterNode.start() completes: report
            # disabled rather than 500 in that window
            return {"enabled": False}
        owned = sum(
            1 for (vhost, name) in cluster.queue_metas
            if cluster.owns_queue(vhost, name))
        return {
            "enabled": True,
            "self": cluster.name,
            "members": {
                name: {"status": member.status,
                       "incarnation": member.incarnation}
                for name, member in cluster.membership.members.items()
            },
            "alive": cluster.membership.alive_members(),
            "known_queues": len(cluster.queue_metas),
            "owned_queues": owned,
            "replication": (
                {"enabled": False} if cluster.replication is None else {
                    "enabled": True,
                    "factor": cluster.replication.factor,
                    "sync": cluster.replication.sync,
                    "lag_events": cluster.replication.total_lag(),
                    "copies": len(cluster.replication.applier.copies),
                }),
        }

    def _replication(self) -> dict:
        """Per-queue replica state: role, follower ack positions, and event
        lag on owned queues; applied position on follower copies."""
        cluster = self.broker.cluster
        if cluster is None or cluster.replication is None:
            return {"enabled": False}
        return cluster.replication.status()

    def _exchanges(self, vhost_name: str) -> list:
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return []
        return [
            {
                "name": exchange.name or "(default)",
                "type": exchange.type,
                "durable": exchange.durable,
                "auto_delete": exchange.auto_delete,
                "internal": exchange.internal,
                "bindings": len(exchange.matcher.bindings()),
                "exchange_bindings": (
                    len(exchange.ex_matcher.bindings())
                    if exchange.ex_matcher is not None else 0),
            }
            for exchange in vhost.exchanges.values()
        ]
