"""Multi-tenancy: /admin/tenants endpoint shapes, SASL handshake edge
cases, ACL denials, quota caps, config fail-closed paths, and the
tenant-labeled observability surface.

Admin conventions under test are the PR 6 set: mutations require POST
(405 otherwise), unknown names are 404, invalid specs are 400, and a
subsystem that is not enabled answers 409 — never a silent empty body.
"""

import asyncio
import json
import struct

import pytest

from chanamq_tpu import tenancy as tenancy_mod
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError, ConnectionClosedError
from chanamq_tpu.config import Config, ConfigError
from chanamq_tpu.rest.admin import AdminServer
from chanamq_tpu.tenancy import TenancyError, TenantRegistry

pytestmark = pytest.mark.asyncio

CONN_REFUSED = (ConnectionClosedError, OSError,
                asyncio.IncompleteReadError, asyncio.TimeoutError)


async def http_req(port: int, path: str, method: str = "GET",
                   body: "dict | bytes | None" = None) -> tuple[int, dict]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = (body if isinstance(body, bytes)
               else json.dumps(body).encode() if body is not None else b"")
    writer.write(
        f"{method} {path} HTTP/1.1\r\nHost: localhost\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 20), 5)
    writer.close()
    head, _, resp = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(resp) if resp else {}


async def http_text(port: int, path: str) -> tuple[int, str]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: localhost\r\n\r\n".encode())
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(1 << 22), 5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), body.decode()


def _attach_registry(server: BrokerServer) -> TenantRegistry:
    registry = TenantRegistry(server.broker)
    server.broker.tenancy = registry
    tenancy_mod.install(registry)
    return registry


@pytest.fixture
async def stack():
    """Broker + admin with tenancy enabled (empty registry)."""
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    registry = _attach_registry(server)
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    yield server, admin, registry
    tenancy_mod.install(None)
    await admin.stop()
    await server.stop()


# ---------------------------------------------------------------------------
# /admin/tenants endpoint shapes (PR 6 conventions)
# ---------------------------------------------------------------------------


async def test_admin_tenants_crud_shapes(stack):
    server, admin, registry = stack
    port = admin.bound_port

    # empty registry snapshot
    status, body = await http_req(port, "/admin/tenants")
    assert status == 200
    assert body == {"tenants": [], "count": 0, "ticks": 0, "decisions": 0}

    # define at runtime: same spec shape as chana.mq.tenant.tenants + name
    status, body = await http_req(port, "/admin/tenants", "POST", {
        "name": "acme", "vhosts": ["acme-vh"], "users": {"alice": "pw"},
        "acls": {"alice": {"acme-vh": ["configure", "write", "read"]}},
        "quota": {"max-queues": 2, "publish-rate": 4096}})
    assert status == 200 and body["ok"]
    snap = body["tenant"]
    assert snap["name"] == "acme"
    assert snap["vhosts"] == ["acme-vh"]
    assert snap["quota"]["max-queues"] == 2
    assert snap["quota"]["publish-burst"] == 8192  # default 2x rate
    assert "acme" in registry.tenants

    # detail + list
    status, body = await http_req(port, "/admin/tenants/acme")
    assert status == 200 and body["name"] == "acme"
    status, body = await http_req(port, "/admin/tenants")
    assert status == 200 and body["count"] == 1

    # 404: unknown tenant (detail and delete)
    status, body = await http_req(port, "/admin/tenants/nope")
    assert status == 404 and "error" in body
    status, body = await http_req(port, "/admin/tenants/nope/delete", "POST")
    assert status == 404 and "error" in body

    # 405: wrong method on the collection and on the delete mutation
    status, body = await http_req(port, "/admin/tenants", "DELETE")
    assert status == 405
    status, body = await http_req(port, "/admin/tenants/acme/delete")
    assert status == 405

    # delete, then the name is gone (404 on a second delete)
    status, body = await http_req(port, "/admin/tenants/acme/delete", "POST")
    assert status == 200 and body["ok"] and body["tenant"] == "acme"
    assert "acme" not in registry.tenants
    status, body = await http_req(port, "/admin/tenants/acme/delete", "POST")
    assert status == 404


async def test_admin_tenants_400_invalid_specs(stack):
    server, admin, registry = stack
    port = admin.bound_port
    registry.define("held", {"vhosts": ["held-vh"], "users": {"bob": "pw"}})

    bad_bodies = [
        b"{not json",                                        # unparseable
        json.dumps({"vhosts": ["v"]}).encode(),              # no name
        json.dumps({"name": "", "vhosts": ["v"]}).encode(),  # empty name
        json.dumps({"name": "t"}).encode(),                  # no vhosts
        json.dumps({"name": "t", "vhosts": ["v"],
                    "quota": {"max-widgets": 1}}).encode(),  # unknown quota
        json.dumps({"name": "t", "vhosts": ["v"],
                    "quota": {"memory-share": 1.5}}).encode(),
        json.dumps({"name": "t", "vhosts": ["v"],
                    "quota": {"publish-burst": 64}}).encode(),  # burst w/o rate
        json.dumps({"name": "t", "vhosts": ["v"],
                    "acls": {"ghost": {"v": ["read"]}}}).encode(),
        json.dumps({"name": "t", "vhosts": ["held-vh"]}).encode(),  # owned
        json.dumps({"name": "t", "vhosts": ["v"],
                    "users": {"bob": "pw2"}}).encode(),      # user owned
    ]
    for raw in bad_bodies:
        status, body = await http_req(port, "/admin/tenants", "POST", raw)
        assert status == 400 and "error" in body, raw
    # nothing leaked into the registry from the refused defines
    assert set(registry.tenants) == {"held"}


async def test_admin_tenants_409_when_disabled():
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await server.start()
    admin = AdminServer(server.broker, port=0)
    await admin.start()
    try:
        for path, method, body in [
                ("/admin/tenants", "GET", None),
                ("/admin/tenants", "POST",
                 {"name": "t", "vhosts": ["v"]}),
                ("/admin/tenants/t", "GET", None),
                ("/admin/tenants/t/delete", "POST", None)]:
            status, resp = await http_req(
                admin.bound_port, path, method, body)
            assert status == 409, (path, method)
            assert "tenant" in resp["error"]
    finally:
        await admin.stop()
        await server.stop()


# ---------------------------------------------------------------------------
# SASL handshake edge cases
# ---------------------------------------------------------------------------


def _method_frame(channel: int, class_id: int, method_id: int,
                  args: bytes) -> bytes:
    payload = struct.pack(">HH", class_id, method_id) + args
    return (struct.pack(">BHI", 1, channel, len(payload))
            + payload + b"\xce")


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


async def _read_frame(reader) -> tuple[int, int, bytes]:
    header = await asyncio.wait_for(reader.readexactly(7), 10)
    ftype, channel, size = struct.unpack(">BHI", header)
    rest = await asyncio.wait_for(reader.readexactly(size + 1), 10)
    assert rest[-1] == 0xCE
    return ftype, channel, rest[:-1]


async def _start_ok(port: int, mechanism: str,
                    response: bytes) -> tuple[int, int, bytes]:
    """Raw handshake through StartOk (the client object always picks
    PLAIN, so EXTERNAL must be driven on the wire); returns the (class,
    method, args) of the server's reply frame."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        writer.write(b"AMQP\x00\x00\x09\x01")
        await _read_frame(reader)  # Connection.Start
        writer.write(_method_frame(
            0, 10, 11,
            struct.pack(">I", 0)            # empty client-properties table
            + _shortstr(mechanism) + _longstr(response) + _shortstr("en_US")))
        _, _, payload = await _read_frame(reader)
        class_id, method_id = struct.unpack(">HH", payload[:4])
        return class_id, method_id, payload[4:]
    finally:
        writer.close()


async def test_sasl_plain_wrong_password_closes_403():
    """PLAIN against the merged user table: a wrong password gets a
    Connection.Close with reply-code 403 (access-refused), and the same
    for a user that does not exist (no user-table oracle)."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       users={"ops": "ops-pw"})
    await srv.start()
    registry = _attach_registry(srv)
    registry.define("acme", {"vhosts": ["acme-vh"],
                             "users": {"alice": "secret"}})
    await srv.broker.create_vhost("acme-vh")
    try:
        for response in (b"\x00alice\x00wrong", b"\x00ghost\x00whatever"):
            class_id, method_id, args = await _start_ok(
                srv.bound_port, "PLAIN", response)
            assert (class_id, method_id) == (10, 50)  # connection.close
            assert struct.unpack(">H", args[:2])[0] == 403
        # the happy paths through the same merged table still work
        c = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="acme-vh",
            username="alice", password="secret")
        await c.close()
        c = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="/",
            username="ops", password="ops-pw")
        await c.close()
        # tenant users are confined to their tenant's vhosts
        with pytest.raises(CONN_REFUSED):
            await AMQPClient.connect(
                "127.0.0.1", srv.bound_port, vhost="/",
                username="alice", password="secret")
    finally:
        tenancy_mod.install(None)
        await srv.stop()


async def test_sasl_external_refused_when_users_configured():
    """EXTERNAL (no in-band credentials) must be refused the moment any
    user table exists — here the only users are tenant-declared, so the
    refusal proves the merged view reaches the SASL seam."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    registry = _attach_registry(srv)
    registry.define("acme", {"vhosts": ["acme-vh"],
                             "users": {"alice": "secret"}})
    try:
        class_id, method_id, args = await _start_ok(
            srv.bound_port, "EXTERNAL", b"")
        assert (class_id, method_id) == (10, 50)
        assert struct.unpack(">H", args[:2])[0] == 403
    finally:
        tenancy_mod.install(None)
        await srv.stop()


async def test_sasl_open_access_when_no_users_anywhere():
    """Reference-parity compatibility path: tenants without user tables
    keep the server open-access — PLAIN with any credentials and even
    EXTERNAL proceed to Tune."""
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    registry = _attach_registry(srv)
    registry.define("quota-only", {"vhosts": ["q-vh"]})
    await srv.broker.create_vhost("q-vh")
    try:
        class_id, method_id, _ = await _start_ok(
            srv.bound_port, "EXTERNAL", b"")
        assert (class_id, method_id) == (10, 30)  # connection.tune
        c = await AMQPClient.connect(
            "127.0.0.1", srv.bound_port, vhost="q-vh",
            username="anyone", password="anything")
        await c.close()
    finally:
        tenancy_mod.install(None)
        await srv.stop()


# ---------------------------------------------------------------------------
# ACL denial -> access-refused (403) on declare / publish / consume
# ---------------------------------------------------------------------------


@pytest.fixture
async def acl_stack():
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    registry = _attach_registry(srv)
    registry.define("acme", {
        "vhosts": ["acme-vh"],
        "users": {"full": "pw", "writer": "pw", "reader": "pw"},
        "acls": {
            "full": {"acme-vh": ["configure", "write", "read"]},
            "writer": {"acme-vh": ["write"]},
            "reader": {"acme-vh": ["read"]},
        }})
    await srv.broker.create_vhost("acme-vh")
    # the full user provisions the topology the restricted users hit
    c = await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                 vhost="acme-vh",
                                 username="full", password="pw")
    ch = await c.channel()
    await ch.queue_declare("aclq")
    await c.close()
    yield srv, registry
    tenancy_mod.install(None)
    await srv.stop()


async def _tenant_conn(srv, user: str) -> AMQPClient:
    return await AMQPClient.connect("127.0.0.1", srv.bound_port,
                                    vhost="acme-vh",
                                    username=user, password="pw")


async def test_acl_configure_denied_on_declare(acl_stack):
    srv, registry = acl_stack
    before = srv.broker.metrics.tenancy_acl_denials_total
    c = await _tenant_conn(srv, "writer")
    try:
        ch = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch.queue_declare("writerq")
        assert exc.value.reply_code == 403
        assert "configure" in exc.value.reply_text
        ch2 = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch2.exchange_declare("writerx", "topic")
        assert exc.value.reply_code == 403
        assert srv.broker.metrics.tenancy_acl_denials_total == before + 2
        assert "writerq" not in srv.broker.vhosts["acme-vh"].queues
    finally:
        await c.close()


async def test_acl_write_denied_on_publish(acl_stack):
    srv, registry = acl_stack
    c = await _tenant_conn(srv, "reader")
    try:
        ch = await c.channel()
        await ch.confirm_select()
        with pytest.raises(ChannelClosedError) as exc:
            await ch.basic_publish_confirmed(b"x", routing_key="aclq")
        assert exc.value.reply_code == 403
        assert "write" in exc.value.reply_text
    finally:
        await c.close()
    # nothing reached the queue, and the refusal was counted
    assert srv.broker.vhosts["acme-vh"].queues["aclq"].message_count == 0
    assert srv.broker.metrics.tenancy_acl_denials_total >= 1


async def test_acl_read_denied_on_consume_and_get(acl_stack):
    srv, registry = acl_stack
    c = await _tenant_conn(srv, "writer")
    try:
        ch = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch.basic_consume("aclq", lambda m: None)
        assert exc.value.reply_code == 403
        assert "read" in exc.value.reply_text
        ch2 = await c.channel()
        with pytest.raises(ChannelClosedError) as exc:
            await ch2.basic_get("aclq")
        assert exc.value.reply_code == 403
    finally:
        await c.close()


async def test_acl_full_permissions_unrestricted(acl_stack):
    srv, registry = acl_stack
    c = await _tenant_conn(srv, "full")
    try:
        ch = await c.channel()
        await ch.confirm_select()
        await ch.basic_publish_confirmed(b"payload", routing_key="aclq")
        got = await ch.basic_get("aclq", no_ack=True)
        assert got is not None and got.body == b"payload"
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# quota caps at the existing mutation sites
# ---------------------------------------------------------------------------


async def test_connection_and_channel_quota_530(stack):
    server, admin, registry = stack
    registry.define("capped", {"vhosts": ["cap-vh"],
                               "quota": {"max-connections": 1,
                                         "max-channels": 2}})
    await server.broker.create_vhost("cap-vh")
    c1 = await AMQPClient.connect("127.0.0.1", server.bound_port,
                                  vhost="cap-vh")
    try:
        # second connection into the tenant's vhost: 530 not-allowed
        with pytest.raises(CONN_REFUSED):
            await AMQPClient.connect("127.0.0.1", server.bound_port,
                                     vhost="cap-vh")
        assert len(registry.tenants["capped"].conns) == 1
        # channels 1 and 2 fit the cap; the third is a connection-level
        # refusal (RabbitMQ's channel-limit shape)
        await c1.channel()
        await c1.channel()
        with pytest.raises(CONN_REFUSED + (ChannelClosedError,)) as exc:
            await c1.channel()
        if isinstance(exc.value, ConnectionClosedError):
            assert exc.value.reply_code == 530
        assert server.broker.metrics.tenancy_quota_refusals_total == 2
    finally:
        await c1.close()


async def test_queue_and_binding_quota_406(stack):
    server, admin, registry = stack
    await server.broker.create_vhost("cap-vh")
    base_bindings = 0  # fresh vhost: nothing bound yet
    registry.define("capped", {
        "vhosts": ["cap-vh"],
        "quota": {"max-queues": 1, "max-bindings": base_bindings + 1}})
    c = await AMQPClient.connect("127.0.0.1", server.bound_port,
                                 vhost="cap-vh")
    try:
        ch = await c.channel()
        await ch.queue_declare("q1")
        # re-declare of an existing queue stays free at the cap
        await ch.queue_declare("q1")
        with pytest.raises(ChannelClosedError) as exc:
            await ch.queue_declare("q2")
        assert exc.value.reply_code == 406
        assert "queue quota" in exc.value.reply_text

        ch = await c.channel()
        await ch.queue_bind("q1", "amq.topic", routing_key="a.#")
        with pytest.raises(ChannelClosedError) as exc:
            await ch.queue_bind("q1", "amq.topic", routing_key="b.#")
        assert exc.value.reply_code == 406
        assert "binding quota" in exc.value.reply_text
    finally:
        await c.close()


# ---------------------------------------------------------------------------
# config fail-closed + env wiring
# ---------------------------------------------------------------------------


async def test_tenancy_config_fails_closed():
    class _B:  # minimal broker stand-in: enable only touches .tenancy
        tenancy = None

    # tenants declared while tenancy is disabled: boot error, never a
    # silently unenforced quota
    with pytest.raises(ConfigError):
        tenancy_mod.enable_from_config(Config(overrides={
            "chana.mq.tenant.tenants": {"t": {"vhosts": ["/"]}}},
            env={}), _B())
    # malformed specs are boot errors too, with the tenant named
    with pytest.raises(ConfigError, match="bad-tenant"):
        tenancy_mod.enable_from_config(Config(overrides={
            "chana.mq.tenant.enabled": True,
            "chana.mq.tenant.tenants": {"bad-tenant": {"vhosts": []}}},
            env={}), _B())
    tenancy_mod.install(None)


async def test_tenancy_env_json_round_trip():
    spec = {"acme": {"vhosts": ["acme-vh"],
                     "quota": {"publish-rate": 4096}}}
    cfg = Config(env={"CHANAMQ_TENANT_ENABLED": "true",
                      "CHANAMQ_TENANT_TENANTS": json.dumps(spec)})

    class _B:
        tenancy = None

    broker = _B()
    registry = tenancy_mod.enable_from_config(cfg, broker)
    try:
        assert broker.tenancy is registry
        assert tenancy_mod.ACTIVE is registry
        tenant = registry.tenants["acme"]
        assert tenant.quota.publish_rate == 4096
        assert tenant.quota.publish_burst == 8192
        assert registry.by_vhost["acme-vh"] is tenant
    finally:
        tenancy_mod.install(None)


def test_registry_define_validation_direct():
    class _B:
        tenancy = None

    registry = TenantRegistry(_B())
    with pytest.raises(TenancyError):
        registry.define("", {"vhosts": ["v"]})
    with pytest.raises(TenancyError):
        registry.define("t", {"vhosts": ["v"], "extras": 1})
    with pytest.raises(TenancyError):
        registry.define("t", {"vhosts": ["v"],
                              "quota": {"max-queues": -1}})
    with pytest.raises(TenancyError):
        registry.define("t", {"vhosts": ["v"],
                              "quota": {"max-queues": True}})
    with pytest.raises(TenancyError):
        registry.define("t", {"vhosts": ["v"], "users": {"u": "pw"},
                              "acls": {"u": {"other-vh": ["read"]}}})
    with pytest.raises(TenancyError):
        registry.define("t", {"vhosts": ["v"], "users": {"u": "pw"},
                              "acls": {"u": {"v": ["admin"]}}})
    assert registry.tenants == {}

    # replacement keeps live state but adopts the new tables
    t1 = registry.define("t", {"vhosts": ["v"], "users": {"u": "pw"}})
    t1.published_folded = 7
    t2 = registry.define("t", {"vhosts": ["v", "v2"],
                               "quota": {"publish-rate": 1024}})
    assert t2 is t1
    assert t2.published_folded == 7
    assert t2.vhosts == ("v", "v2")
    assert registry.by_vhost["v2"] is t1
    assert registry.remove("t") and not registry.remove("t")
    assert registry.by_vhost == {} and registry.by_user == {}


# ---------------------------------------------------------------------------
# tenant-labeled observability surface
# ---------------------------------------------------------------------------


async def test_prometheus_tenant_series(stack):
    server, admin, registry = stack
    registry.define("acme", {"vhosts": ["acme-vh"],
                             "quota": {"publish-rate": 4096}})
    await server.broker.create_vhost("acme-vh")
    c = await AMQPClient.connect("127.0.0.1", server.bound_port,
                                 vhost="acme-vh")
    ch = await c.channel()
    await ch.confirm_select()
    await ch.queue_declare("pq")
    await ch.basic_publish_confirmed(b"x" * 64, routing_key="pq")

    status, text = await http_text(admin.bound_port, "/metrics")
    assert status == 200
    lines = text.splitlines()
    metrics = {}
    for line in lines:
        if line.startswith("#") or not line:
            continue
        name, _, value = line.rpartition(" ")
        metrics[name] = float(value)
    assert metrics["chanamq_tenancy_tenants"] == 1
    assert metrics['chanamq_tenant_connections{tenant="acme"}'] == 1
    assert metrics['chanamq_tenant_published{tenant="acme"}'] == 1
    assert metrics['chanamq_tenant_gated{tenant="acme"}'] == 0
    assert metrics['chanamq_tenant_tokens{tenant="acme"}'] <= 8192
    # queue series on a tenant-owned vhost carry the tenant label
    assert metrics[
        'chanamq_queue_messages{vhost="acme-vh",queue="pq",'
        'tenant="acme"}'] == 1
    await c.close()


async def test_timeseries_tenant_rows(stack):
    from chanamq_tpu.telemetry import TelemetryService

    server, admin, registry = stack
    registry.define("acme", {"vhosts": ["acme-vh"]})
    svc = TelemetryService(server.broker, interval_s=3600.0)
    server.broker.telemetry = svc
    try:
        status, body = await http_req(
            admin.bound_port, "/admin/timeseries?scope=local")
        assert status == 200
        rows = body["nodes"][server.broker.trace_node]["tenants"]
        assert [r["name"] for r in rows] == ["acme"]
        assert rows[0]["vhosts"] == ["acme-vh"]
    finally:
        server.broker.telemetry = None
