"""Queue-argument extensions: dead-letter exchanges, length/byte caps with
drop-head overflow, and idle queue auto-expiry (x-expires).

All EXCEED the reference, whose only queue argument is x-message-ttl
(QueueEntity.scala:288-297). Semantics follow RabbitMQ: x-death headers
accumulate per (queue, reason), automatic deaths (expired/maxlen) never
cycle, per-message expiration is cleared on dead-lettering, and caps bound
READY messages with oldest-first drop.
"""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.broker import Broker
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.client.client import ChannelClosedError

pytestmark = pytest.mark.asyncio


@pytest.fixture
async def server():
    srv = BrokerServer(broker=Broker(message_sweep_interval_s=0.1),
                       host="127.0.0.1", port=0, heartbeat_s=0)
    await srv.start()
    yield srv
    await srv.stop()


@pytest.fixture
async def client(server):
    c = await AMQPClient.connect("127.0.0.1", server.bound_port)
    yield c
    await c.close()


async def drain(ch, queue, n, timeout=3.0):
    out = []
    deadline = asyncio.get_event_loop().time() + timeout
    while len(out) < n and asyncio.get_event_loop().time() < deadline:
        msg = await ch.basic_get(queue, no_ack=True)
        if msg is None:
            await asyncio.sleep(0.02)
            continue
        out.append(msg)
    return out


async def declare_dlq(ch, dlq="dlq"):
    await ch.exchange_declare("dlx_ex", "fanout")
    await ch.queue_declare(dlq)
    await ch.queue_bind(dlq, "dlx_ex", "")


# -- max-length ------------------------------------------------------------


async def test_max_length_drops_oldest(client):
    ch = await client.channel()
    await ch.queue_declare("cap_q", arguments={"x-max-length": 3})
    for i in range(5):
        ch.basic_publish(b"m%d" % i, routing_key="cap_q")
    await asyncio.sleep(0.05)
    ok = await ch.queue_declare("cap_q", passive=True)
    assert ok.message_count == 3
    bodies = [m.body for m in await drain(ch, "cap_q", 3)]
    assert bodies == [b"m2", b"m3", b"m4"]


async def test_max_length_bytes_drops_oldest(client):
    ch = await client.channel()
    await ch.queue_declare("capb_q", arguments={"x-max-length-bytes": 250})
    for i in range(4):
        ch.basic_publish(bytes([48 + i]) * 100, routing_key="capb_q")
    await asyncio.sleep(0.05)
    ok = await ch.queue_declare("capb_q", passive=True)
    assert ok.message_count == 2  # 2x100 <= 250 < 3x100
    bodies = [m.body for m in await drain(ch, "capb_q", 2)]
    assert bodies == [b"2" * 100, b"3" * 100]


async def test_maxlen_overflow_dead_letters(client):
    ch = await client.channel()
    await declare_dlq(ch)
    await ch.queue_declare("capd_q", arguments={
        "x-max-length": 1, "x-dead-letter-exchange": "dlx_ex"})
    ch.basic_publish(b"first", routing_key="capd_q")
    ch.basic_publish(b"second", routing_key="capd_q")
    got = await drain(ch, "dlq", 1)
    assert [m.body for m in got] == [b"first"]
    death = got[0].properties.headers["x-death"][0]
    assert death["queue"] == "capd_q"
    assert death["reason"] == "maxlen"
    assert death["count"] == 1


# -- dead-letter on expiry and reject --------------------------------------


async def test_ttl_expiry_dead_letters_with_x_death(client):
    ch = await client.channel()
    await declare_dlq(ch)
    await ch.queue_declare("ttl_q", arguments={
        "x-message-ttl": 60, "x-dead-letter-exchange": "dlx_ex",
        "x-dead-letter-routing-key": "was-ttl"})
    ch.basic_publish(b"doomed", routing_key="ttl_q",
                     properties=BasicProperties(expiration="60"))
    got = await drain(ch, "dlq", 1)
    assert [m.body for m in got] == [b"doomed"]
    msg = got[0]
    assert msg.routing_key == "was-ttl"
    # expiration cleared so it cannot instantly re-expire in the DLQ
    assert msg.properties.expiration is None
    death = msg.properties.headers["x-death"][0]
    assert death["reason"] == "expired"
    assert death["queue"] == "ttl_q"
    assert death["routing-keys"] == ["ttl_q"]
    assert msg.properties.headers["x-first-death-reason"] == "expired"
    assert msg.properties.headers["x-first-death-queue"] == "ttl_q"


async def test_reject_dead_letters(client):
    ch = await client.channel()
    await declare_dlq(ch)
    await ch.queue_declare("rej_q", arguments={
        "x-dead-letter-exchange": "dlx_ex"})
    ch.basic_publish(b"bad", routing_key="rej_q")
    msg = await (await drain_one(ch, "rej_q"))
    ch.basic_reject(msg.delivery_tag, requeue=False)
    got = await drain(ch, "dlq", 1)
    assert [m.body for m in got] == [b"bad"]
    death = got[0].properties.headers["x-death"][0]
    assert death["reason"] == "rejected"


async def drain_one(ch, queue, timeout=3.0):
    async def inner():
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            msg = await ch.basic_get(queue)
            if msg is not None:
                return msg
            await asyncio.sleep(0.02)
        return None
    return inner()


async def test_nack_requeue_false_dead_letters_and_count_increments(client):
    """A reject cycle through the same queue increments the x-death count
    (client-driven rejects may legally cycle)."""
    ch = await client.channel()
    await ch.exchange_declare("back_ex", "fanout")
    await ch.queue_declare("cycle_q", arguments={
        "x-dead-letter-exchange": "back_ex"})
    await ch.queue_bind("cycle_q", "back_ex", "")  # DLX routes BACK to cycle_q
    ch.basic_publish(b"again", routing_key="cycle_q")
    for expected_count in (1, 2):
        msg = await (await drain_one(ch, "cycle_q"))
        assert msg is not None
        ch.basic_nack(msg.delivery_tag, requeue=False)
        await asyncio.sleep(0.1)
    msg = await (await drain_one(ch, "cycle_q"))
    assert msg is not None
    death = msg.properties.headers["x-death"][0]
    assert death["reason"] == "rejected" and death["count"] == 2


async def test_automatic_death_does_not_cycle(server, client):
    """expired/maxlen dead-letters that route back to the same queue drop on
    the second pass instead of looping forever."""
    ch = await client.channel()
    await ch.exchange_declare("loopback_ex", "fanout")
    await ch.queue_declare("loop_q", arguments={
        "x-message-ttl": 50, "x-dead-letter-exchange": "loopback_ex"})
    await ch.queue_bind("loop_q", "loopback_ex", "")
    ch.basic_publish(b"once-around", routing_key="loop_q")
    await asyncio.sleep(1.0)  # several sweep + TTL cycles
    # first expiry forwarded it back to loop_q (x-death count 1); there it
    # re-queued WITHOUT expiration... but queue TTL still applies, so the
    # second expiry sees the (loop_q, expired) entry and drops it
    ok = await ch.queue_declare("loop_q", passive=True)
    assert ok.message_count == 0
    assert server.broker.metrics.dead_lettered_msgs == 1


async def test_dlx_to_missing_exchange_drops(client):
    ch = await client.channel()
    await ch.queue_declare("noex_q", arguments={
        "x-max-length": 0, "x-dead-letter-exchange": "ghost_ex"})
    ch.basic_publish(b"void", routing_key="noex_q")
    await asyncio.sleep(0.1)
    ok = await ch.queue_declare("noex_q", passive=True)
    assert ok.message_count == 0  # dropped, broker healthy
    ch.basic_publish(b"still-works", routing_key="noex_q")
    await asyncio.sleep(0.05)


# -- x-expires -------------------------------------------------------------


async def test_queue_idle_expiry(client):
    ch = await client.channel()
    await ch.queue_declare("idle_q", arguments={"x-expires": 300})
    ch.basic_publish(b"x", routing_key="idle_q")
    await asyncio.sleep(1.0)  # > x-expires + sweep interval
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.queue_declare("idle_q", passive=True)
    assert exc_info.value.reply_code == 404


async def test_queue_with_consumer_does_not_idle_expire(client):
    ch = await client.channel()
    await ch.queue_declare("busy_q", arguments={"x-expires": 300})
    await ch.basic_consume("busy_q", lambda m: None)
    await asyncio.sleep(1.0)
    ok = await ch.queue_declare("busy_q", passive=True)
    assert ok.queue == "busy_q"  # alive: consumer pins it


async def test_use_resets_idle_clock(client):
    ch = await client.channel()
    await ch.queue_declare("pinged_q", arguments={"x-expires": 600})
    for _ in range(4):
        await asyncio.sleep(0.3)
        await ch.basic_get("pinged_q")  # use resets the clock
    ok = await ch.queue_declare("pinged_q", passive=True)
    assert ok.queue == "pinged_q"


# -- validation ------------------------------------------------------------


async def test_invalid_arguments_rejected(client):
    cases = [
        {"x-max-length": -1},
        {"x-max-length-bytes": "big"},
        {"x-expires": 0},
        {"x-dead-letter-exchange": 7},
        {"x-dead-letter-routing-key": "rk"},  # without x-dead-letter-exchange
        {"x-overflow": "reject-publish"},
    ]
    for args in cases:
        ch = await client.channel()
        with pytest.raises(ChannelClosedError) as exc_info:
            await ch.queue_declare("bad_q", arguments=args)
        assert exc_info.value.reply_code == 406, args


async def test_retry_topology_survives_multiple_passes(client):
    """Work queue -> TTL retry queue -> work queue: a history containing an
    explicit reject is a client-driven retry loop and must keep flowing
    (only FULLY automatic cycles are suppressed)."""
    ch = await client.channel()
    await ch.exchange_declare("work_dlx", "fanout")
    await ch.exchange_declare("retry_dlx", "fanout")
    await ch.queue_declare("work_q", arguments={
        "x-dead-letter-exchange": "work_dlx"})
    await ch.queue_declare("retry_q", arguments={
        "x-message-ttl": 60, "x-dead-letter-exchange": "retry_dlx"})
    await ch.queue_bind("retry_q", "work_dlx", "")
    await ch.queue_bind("work_q", "retry_dlx", "")

    ch.basic_publish(b"job", routing_key="work_q")
    for attempt in (1, 2, 3):
        msg = await (await drain_one(ch, "work_q", timeout=5.0))
        assert msg is not None, f"retry attempt {attempt} never redelivered"
        ch.basic_reject(msg.delivery_tag, requeue=False)
    # after 3 rejects the job has cycled work->retry->work 3 times; the
    # x-death history shows both the rejects and the retry-queue expiries
    msg = await (await drain_one(ch, "work_q", timeout=5.0))
    assert msg is not None
    deaths = {(d["queue"], d["reason"]): d["count"]
              for d in msg.properties.headers["x-death"]}
    assert deaths[("work_q", "rejected")] == 3
    assert deaths[("retry_q", "expired")] == 3


async def test_dlx_default_exchange_routes_to_named_queue(client):
    """x-dead-letter-exchange \"\" with a routing key is the standard
    RabbitMQ pattern for dead-lettering straight into a named queue via
    the default exchange."""
    ch = await client.channel()
    await ch.queue_declare("direct_dlq")
    await ch.queue_declare("dd_q", arguments={
        "x-dead-letter-exchange": "",
        "x-dead-letter-routing-key": "direct_dlq",
        "x-max-length": 0})
    ch.basic_publish(b"straight", routing_key="dd_q")
    got = await drain(ch, "direct_dlq", 1)
    assert [m.body for m in got] == [b"straight"]
    assert got[0].properties.headers["x-death"][0]["reason"] == "maxlen"


async def test_queue_extension_arguments_survive_restart(tmp_path):
    """Caps and DLX wiring on a durable queue are recovered from the store:
    after a restart the max-length still drops to the DLX."""
    from chanamq_tpu.store.sqlite import SqliteStore

    db_path = str(tmp_path / "args.db")
    srv = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                       store=SqliteStore(db_path))
    await srv.start()
    try:
        c = await AMQPClient.connect("127.0.0.1", srv.bound_port)
        ch = await c.channel()
        await ch.exchange_declare("ra_dlx", "fanout", durable=True)
        await ch.queue_declare("ra_dlq", durable=True)
        await ch.queue_bind("ra_dlq", "ra_dlx", "")
        await ch.queue_declare("ra_q", durable=True, arguments={
            "x-max-length": 1, "x-dead-letter-exchange": "ra_dlx"})
        await c.close()
    finally:
        await srv.stop()

    srv2 = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                        store=SqliteStore(db_path))
    await srv2.start()
    try:
        c2 = await AMQPClient.connect("127.0.0.1", srv2.bound_port)
        ch2 = await c2.channel()
        ch2.basic_publish(b"one", routing_key="ra_q",
                          properties=BasicProperties(delivery_mode=2))
        ch2.basic_publish(b"two", routing_key="ra_q",
                          properties=BasicProperties(delivery_mode=2))
        got = await drain(ch2, "ra_dlq", 1)
        assert [m.body for m in got] == [b"one"]
        assert got[0].properties.headers["x-death"][0]["reason"] == "maxlen"
        ok = await ch2.queue_declare("ra_q", passive=True)
        assert ok.message_count == 1
        await c2.close()
    finally:
        await srv2.stop()


# -- consumer priorities (x-priority consume argument) ----------------------


async def test_consumer_priority_preferred_while_it_has_budget(server):
    """x-priority consumers are served first while they have prefetch
    budget; deliveries spill to lower priorities when the window is full
    (RabbitMQ consumer-priority semantics; the reference round-robins
    only)."""
    from chanamq_tpu.client import AMQPClient as _C

    c_hi = await _C.connect("127.0.0.1", server.bound_port)
    c_lo = await _C.connect("127.0.0.1", server.bound_port)
    try:
        setup = await c_hi.channel()
        await setup.queue_declare("prio_q")

        hi_got, lo_got = [], []
        ch_hi = await c_hi.channel()
        await ch_hi.basic_qos(prefetch_count=2)
        await ch_hi.basic_consume("prio_q", hi_got.append,
                                  arguments={"x-priority": 10})
        ch_lo = await c_lo.channel()
        await ch_lo.basic_qos(prefetch_count=100)
        await ch_lo.basic_consume("prio_q", lo_got.append)

        for i in range(6):
            setup.basic_publish(b"p%d" % i, routing_key="prio_q")
        await asyncio.sleep(0.3)
        # high priority takes its full window of 2; the rest spill to low
        assert len(hi_got) == 2, (hi_got, lo_got)
        assert len(lo_got) == 4
        assert [m.body for m in hi_got] == [b"p0", b"p1"]
        # acking frees the window: the next message prefers high again
        for m in hi_got:
            ch_hi.basic_ack(m.delivery_tag)
        setup.basic_publish(b"p6", routing_key="prio_q")
        await asyncio.sleep(0.2)
        assert [m.body for m in hi_got[2:]] == [b"p6"]
    finally:
        await c_hi.close()
        await c_lo.close()


async def test_consumer_priority_invalid_argument_rejected(client):
    ch = await client.channel()
    await ch.queue_declare("prio_bad_q")
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.basic_consume("prio_bad_q", lambda m: None,
                               arguments={"x-priority": "high"})
    assert exc_info.value.reply_code == 406


async def test_consumer_priority_round_robin_within_level(server):
    """Spills below a busy high-priority consumer still round-robin across
    ALL lower-level siblings (per-level rotation indexes)."""
    from chanamq_tpu.client import AMQPClient as _C

    c_hi = await _C.connect("127.0.0.1", server.bound_port)
    c_lo = await _C.connect("127.0.0.1", server.bound_port)
    try:
        setup = await c_hi.channel()
        await setup.queue_declare("prio_rr_q")
        ch_hi = await c_hi.channel()
        await ch_hi.basic_qos(prefetch_count=1)
        hi_got = []
        await ch_hi.basic_consume("prio_rr_q", hi_got.append,
                                  arguments={"x-priority": 10})
        counts = {"a": 0, "b": 0, "c": 0}
        ch_lo = await c_lo.channel()
        for name in counts:
            def mk(n):
                return lambda m: counts.__setitem__(n, counts[n] + 1)
            await ch_lo.basic_consume("prio_rr_q", mk(name), no_ack=True,
                                      consumer_tag=f"lo-{name}")
        for i in range(10):
            setup.basic_publish(b"m%d" % i, routing_key="prio_rr_q")
        await asyncio.sleep(0.3)
        # high takes 1 (window full, never acked); 9 spill across a/b/c
        assert len(hi_got) == 1
        assert sum(counts.values()) == 9
        assert all(v >= 2 for v in counts.values()), counts
    finally:
        await c_hi.close()
        await c_lo.close()


# -- single-active consumer (x-single-active-consumer) ----------------------


async def test_single_active_consumer_exclusive_delivery_and_takeover(server):
    """SAC: only the longest-registered consumer receives; cancelling it
    hands the queue to the next registrant, and a consumer-connection
    death does the same."""
    from chanamq_tpu.client import AMQPClient as _C

    c1 = await _C.connect("127.0.0.1", server.bound_port)
    c2 = await _C.connect("127.0.0.1", server.bound_port)
    c3 = await _C.connect("127.0.0.1", server.bound_port)
    try:
        setup = await c1.channel()
        await setup.queue_declare("sac_q", arguments={
            "x-single-active-consumer": True})
        a_got, b_got, c_got = [], [], []
        ch_a = await c1.channel()
        tag_a = await ch_a.basic_consume("sac_q", a_got.append, no_ack=True)
        ch_b = await c2.channel()
        await ch_b.basic_consume("sac_q", b_got.append, no_ack=True)
        ch_c = await c3.channel()
        await ch_c.basic_consume("sac_q", c_got.append, no_ack=True)

        for i in range(6):
            setup.basic_publish(b"m%d" % i, routing_key="sac_q")
        await asyncio.sleep(0.2)
        assert len(a_got) == 6 and not b_got and not c_got

        # cancel the active consumer: B takes over
        await ch_a.basic_cancel(tag_a)
        setup.basic_publish(b"next", routing_key="sac_q")
        await asyncio.sleep(0.2)
        assert [m.body for m in b_got] == [b"next"] and not c_got

        # kill B's connection: C takes over
        await c2.close()
        await asyncio.sleep(0.2)
        setup.basic_publish(b"last", routing_key="sac_q")
        await asyncio.sleep(0.2)
        assert [m.body for m in c_got] == [b"last"]
    finally:
        await c1.close()
        await c3.close()


async def test_single_active_consumer_validation(client):
    ch = await client.channel()
    with pytest.raises(ChannelClosedError) as exc_info:
        await ch.queue_declare("sac_bad", arguments={
            "x-single-active-consumer": "yes"})
    assert exc_info.value.reply_code == 406


async def test_single_active_consumer_prefers_highest_priority(server):
    """SAC + x-priority: the ACTIVE consumer is the highest-priority one
    (RabbitMQ 3.12+ activation rule), even if registered later."""
    from chanamq_tpu.client import AMQPClient as _C

    c1 = await _C.connect("127.0.0.1", server.bound_port)
    c2 = await _C.connect("127.0.0.1", server.bound_port)
    try:
        setup = await c1.channel()
        await setup.queue_declare("sacp_q", arguments={
            "x-single-active-consumer": True})
        low_got, high_got = [], []
        ch_low = await c1.channel()
        await ch_low.basic_consume("sacp_q", low_got.append, no_ack=True)
        ch_high = await c2.channel()
        tag_high = await ch_high.basic_consume(
            "sacp_q", high_got.append, no_ack=True,
            arguments={"x-priority": 10})
        for i in range(4):
            setup.basic_publish(b"p%d" % i, routing_key="sacp_q")
        await asyncio.sleep(0.2)
        assert len(high_got) == 4 and not low_got
        # cancelling the high-priority active hands back to the low one
        await ch_high.basic_cancel(tag_high)
        setup.basic_publish(b"after", routing_key="sacp_q")
        await asyncio.sleep(0.2)
        assert [m.body for m in low_got] == [b"after"]
    finally:
        await c1.close()
        await c2.close()
