"""Advanced delivery semantics (EXCEEDS the reference, which implements
none of these: no Tx class handling beyond stubs, no Exchange.Bind, no
dead-lettering, FIFO-only queues — FrameStage.scala:1023-1027, SURVEY.md).

This package holds the data structures behind the PR 17 feature set; the
broker/channel wiring lives at the existing call sites so the disabled
path stays a pointer check:

- ``PriorityFan`` (priority.py): the ready-list for x-max-priority
  queues — a per-priority fan of deques replacing the single deque, so
  enqueue and dispatch are O(1) instead of an ordered insert scan.
- ``TimerWheel`` / ``DelayService`` (delay.py): x-delay delayed
  delivery — publishes park in a hashed timer wheel and re-enter the
  normal publish path when their delay elapses.
- ``would_create_cycle`` (graph.py): bind-time cycle refusal for
  exchange-to-exchange binding graphs (406 PRECONDITION_FAILED), so the
  compiled router only ever sees a DAG.

Transactions (Tx.Select/Commit/Rollback) ride the WAL scope primitives
(wal/engine.py tx_begin/tx_seal) from AMQPConnection._tx_commit: every
store mutation a commit stages lands in ONE ``tx_batch`` record, which
is what makes a SIGKILL mid-commit all-or-nothing on replay.

Master switch: ``chana.mq.semantics.enabled`` (CHANAMQ_SEMANTICS_ENABLED).
Off removes the per-publish x-delay probe and the bind-time cycle
refusal (the runtime visited-set walk still terminates cycles); priority
ordering and dead-lettering are queue-argument driven and stay on.
"""

from .delay import DelayService, TimerWheel, parse_delay
from .graph import e2e_destinations, would_create_cycle
from .priority import PriorityFan

__all__ = [
    "DelayService",
    "PriorityFan",
    "TimerWheel",
    "e2e_destinations",
    "parse_delay",
    "would_create_cycle",
]
