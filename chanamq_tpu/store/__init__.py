"""L5: pluggable persistence.

Rebuilds the capability of the reference's store layer
(chana-mq-server .../store/package.scala:15-43 `DBOpService` trait and its
CassandraOpService implementation): durable exchanges, queues, bindings,
vhosts, refcounted message blobs, per-queue message logs keyed by offset, a
lastConsumed watermark, unacked bookkeeping, and archival copies on queue
delete. Backends: in-memory (transient/testing) and SQLite (durable).
"""

from .api import StoreService, StoredQueue, StoredExchange, StoredMessage
from .memory import MemoryStore
from .sqlite import SqliteStore

__all__ = [
    "StoreService",
    "StoredQueue",
    "StoredExchange",
    "StoredMessage",
    "MemoryStore",
    "SqliteStore",
]
