"""EventBus and Firehose: internal transitions as real AMQP messages.

Both publish through ``Broker.push_local`` — the same single local-enqueue
block every client publish already flows through — so delivered events ride
the ordinary dispatch/QoS/credit machinery and cost nothing special. The
system exchanges they publish into (``amq.chanamq.event`` and
``amq.chanamq.trace``) are part of every vhost's predeclared set
(broker/entities.py VHost.PREDECLARED); the existing ``amq.*`` name guard
makes them undeclarable and undeletable by clients, while binding to them
is ordinary Queue.Bind.

Determinism: the bus assigns a per-bus monotonically increasing ``seq`` and
stamps the emitting node, so two same-seed soak runs produce identical
event sequences once wall-clock ``ts`` fields are masked (the same
"deterministic mod timestamps" bar the chaos plan and decision logs set).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Optional

from ..amqp.properties import BasicProperties

log = logging.getLogger("chanamq.events")

EVENT_EXCHANGE = "amq.chanamq.event"
TRACE_EXCHANGE = "amq.chanamq.trace"


class EventBus:
    """Publishes internal events into the ``amq.chanamq.event`` exchange.

    ``emit`` is synchronous and cheap: one topic-trie walk; when nothing is
    bound the event is counted dropped and no allocation happens. Hook
    sites are all off the per-message hot path (alert ticks, control
    decisions, stage transitions, ...), so emitting inline keeps ordering
    exact without a flush task.
    """

    def __init__(self, broker, vhost: str = "/") -> None:
        self.broker = broker
        self.vhost = vhost
        self.seq = 0
        # loop captured for emit_threadsafe (the profiler's sampler thread
        # reports slow callbacks from off-loop); None until a loop exists
        try:
            self._loop: Optional[asyncio.AbstractEventLoop] = (
                asyncio.get_event_loop())
        except RuntimeError:
            self._loop = None
        self._loop_thread = threading.get_ident()

    # -- emission ----------------------------------------------------------

    def emit(self, routing_key: str, payload: dict,
             vhost_name: Optional[str] = None) -> bool:
        """Publish one event. Returns True iff it reached >= 1 queue."""
        broker = self.broker
        metrics = broker.metrics
        try:
            vhost = broker.vhosts.get(vhost_name or self.vhost)
            if vhost is None:
                metrics.events_dropped_total += 1
                return False
            exchange = vhost.exchanges.get(EVENT_EXCHANGE)
            if exchange is None:
                metrics.events_dropped_total += 1
                return False
            names = exchange.matcher.route(routing_key)
            # tenant-scoped subscriptions: when the event's vhost belongs
            # to a tenant, the same event ALSO routes under
            # tenant.<name>.<key> — one extra trie walk, only for events
            # carrying a tenant-owned vhost, and only when tenancy is on
            tenant = None
            registry = getattr(broker, "tenancy", None)
            if registry is not None:
                tenant = registry.tenant_of_vhost(payload.get("vhost"))
                if tenant is not None:
                    names = names | exchange.matcher.route(
                        f"tenant.{tenant}.{routing_key}")
            queues = [vhost.queues[n] for n in names if n in vhost.queues]
            if not queues:
                # nothing bound (or bound queues not local): O(1) drop —
                # no body built, no Message allocated
                metrics.events_dropped_total += 1
                return False
            self.seq += 1
            # envelope fields win over payload keys of the same name (an
            # alert payload carries its own "event": fired/resolved)
            envelope = {**payload, "event": routing_key,
                        "node": broker.trace_node,
                        "seq": self.seq, "ts": round(time.time(), 3)}
            if tenant is not None:
                envelope["tenant"] = tenant
            body = json.dumps(
                envelope,
                separators=(",", ":"), sort_keys=True, default=str,
            ).encode()
            props = BasicProperties(
                content_type="application/json", delivery_mode=1,
                app_id="chanamq.events")
            broker.push_local(
                queues, props, body, EVENT_EXCHANGE, routing_key, None, None)
            metrics.events_published_total += 1
            return True
        except Exception:
            # an observability seam must never take down the subsystem it
            # observes; count it and move on
            metrics.events_dropped_total += 1
            log.debug("event emit failed for %s", routing_key, exc_info=True)
            return False

    def emit_threadsafe(self, routing_key: str, payload: dict) -> None:
        """Emit from a non-loop thread (profiler sampler): hop onto the
        loop so queue state is only ever touched from the loop thread."""
        if threading.get_ident() == self._loop_thread or self._loop is None:
            self.emit(routing_key, payload)
            return
        try:
            self._loop.call_soon_threadsafe(self.emit, routing_key, payload)
        except RuntimeError:
            pass  # loop already closed: shutdown race, drop silently

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        m = self.broker.metrics
        return {
            "vhost": self.vhost,
            "exchange": EVENT_EXCHANGE,
            "seq": self.seq,
            "published": m.events_published_total,
            "dropped": m.events_dropped_total,
        }


class Firehose:
    """Per-message tap: publishes/deliveries republished into
    ``amq.chanamq.trace``.

    Exclusions and bounds:

    - messages whose source exchange is ``amq.chanamq.*`` are never tapped
      (the firehose cannot tap its own output or the event bus — no
      recursion);
    - taps stop the moment the flow accountant leaves stage 0: a slow
      firehose consumer grows its queue, the accounted bytes raise the
      stage, and the tap sheds instead of compounding the pressure;
    - ``queue_filter`` (a queue-name prefix) narrows the tap to matching
      queues.
    """

    def __init__(self, broker, vhost: str = "/",
                 queue_filter: str = "", tenant_filter: str = "") -> None:
        self.broker = broker
        self.vhost = vhost
        self.queue_filter = queue_filter
        # chana.mq.firehose.tenant: narrow the tap to traffic on vhosts
        # owned by one tenant (resolved live against broker.tenancy, so
        # runtime tenant changes apply to the next tap)
        self.tenant_filter = tenant_filter
        # ``tap_bindings`` is the hot-path gate both seams read before
        # calling into the firehose at all: the trace exchange matcher's
        # live binding table (identity-stable, mutated in place), so an
        # enabled-but-unconsumed firehose costs one attribute load plus a
        # dict bool test per seam — no method call, no allocation, no trie
        # walk. Falsy (or None when the vhost doesn't exist yet) = no tap.
        self.tap_bindings: "dict | None" = None
        self.refresh()

    def refresh(self) -> None:
        """(Re)resolve the trace exchange's binding table. Called at
        construction and whenever the target vhost is created or deleted
        (a recreated vhost gets a fresh matcher object, so the cached
        table would otherwise go stale)."""
        vhost = self.broker.vhosts.get(self.vhost)
        exchange = vhost.exchanges.get(TRACE_EXCHANGE) if vhost else None
        self.tap_bindings = (
            exchange.matcher.binding_table if exchange is not None else None)

    def _tap(self, routing_key: str, body: bytes, headers: dict) -> None:
        broker = self.broker
        metrics = broker.metrics
        flow = broker.flow
        if flow is not None and flow.stage > 0:
            metrics.firehose_dropped_total += 1
            return
        vhost = broker.vhosts.get(self.vhost)
        if vhost is None:
            return
        exchange = vhost.exchanges.get(TRACE_EXCHANGE)
        if exchange is None:
            return
        names = exchange.matcher.route(routing_key)
        queues = [vhost.queues[n] for n in names if n in vhost.queues]
        if not queues:
            metrics.firehose_dropped_total += 1
            return
        try:
            props = BasicProperties(
                headers=headers, delivery_mode=1, app_id="chanamq.firehose")
            broker.push_local(
                queues, props, body, TRACE_EXCHANGE, routing_key, None, None)
            metrics.firehose_published_total += 1
        except Exception:
            metrics.firehose_dropped_total += 1
            log.debug("firehose tap failed for %s", routing_key,
                      exc_info=True)

    def _tenant_owns(self, vhost_name: str) -> bool:
        registry = getattr(self.broker, "tenancy", None)
        return (registry is not None
                and registry.tenant_of_vhost(vhost_name)
                == self.tenant_filter)

    def tap_publish(self, exchange_name: str, routing_key: str,
                    body: bytes, queues: list) -> None:
        """Called from Broker.push_local after the normal enqueues (only
        when ``tap_bindings`` is truthy — the seam checks)."""
        if exchange_name.startswith("amq.chanamq."):
            return
        if self.queue_filter and not any(
                q.name.startswith(self.queue_filter) for q in queues):
            return
        if self.tenant_filter and not (
                queues and self._tenant_owns(queues[0].vhost)):
            # push_local enqueues within one vhost: the first queue's
            # vhost is the publish's vhost
            return
        key = f"publish.{exchange_name}" if exchange_name else "publish"
        self._tap(key, body, {
            "exchange": exchange_name, "routing_key": routing_key,
            "node": self.broker.trace_node})

    def tap_deliver(self, queue_name: str, exchange_name: str,
                    routing_key: str, body: bytes,
                    vhost_name: str = "") -> None:
        """Called from ServerChannel.deliver as the frame is rendered
        (only when ``tap_bindings`` is truthy — the seam checks)."""
        if exchange_name.startswith("amq.chanamq."):
            return
        if self.queue_filter and not queue_name.startswith(self.queue_filter):
            return
        if self.tenant_filter and not self._tenant_owns(vhost_name):
            return
        self._tap(f"deliver.{queue_name}", body, {
            "queue": queue_name, "exchange": exchange_name,
            "routing_key": routing_key, "node": self.broker.trace_node})
