"""SQLite StoreService — the durable backend.

Capability parity with the reference's CassandraOpService
(chana-mq-server .../store/cassandra/CassandraOpService.scala:46-756): same
schema shape — message blobs + refcount, queue log keyed (queue, offset),
queue metas with a lastConsumed watermark, unacks, binds, vhosts, and
*_deleted archival copies on queue delete (pendingDeleteQueue,
CassandraOpService.scala:561-604).

Design difference from the reference, on purpose: the reference's `execute`
blocked its calling thread while pretending to be async
(CassandraOpService.scala:753-755). Here every operation runs on ONE
dedicated writer thread (FIFO), so (a) the asyncio event loop never blocks,
and (b) writes are strictly ordered — the explicit write-ordering story
SURVEY.md §7.3 calls for. TTL expiry is a stored expire_at timestamp filtered
on read (the analogue of Cassandra row TTL).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional, TypeVar

from .api import StoredExchange, StoredMessage, StoredQueue, StoreService

T = TypeVar("T")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS msgs (
  id INTEGER PRIMARY KEY, header BLOB, body BLOB,
  exchange TEXT, routing_key TEXT, refer_count INTEGER, ttl_ms INTEGER
);
CREATE TABLE IF NOT EXISTS queue_metas (
  vhost TEXT, name TEXT, durable INTEGER, exclusive_ INTEGER,
  auto_delete INTEGER, ttl_ms INTEGER, last_consumed INTEGER,
  arguments TEXT, PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS queue_msgs (
  vhost TEXT, queue TEXT, offset INTEGER, msg_id INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, offset)
);
CREATE TABLE IF NOT EXISTS queue_unacks (
  vhost TEXT, queue TEXT, msg_id INTEGER, offset INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, msg_id)
);
CREATE TABLE IF NOT EXISTS exchanges (
  vhost TEXT, name TEXT, type TEXT, durable INTEGER,
  auto_delete INTEGER, internal INTEGER, arguments TEXT,
  PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS binds (
  vhost TEXT, exchange TEXT, queue TEXT, routing_key TEXT, arguments TEXT,
  PRIMARY KEY (vhost, exchange, queue, routing_key)
);
CREATE TABLE IF NOT EXISTS vhosts (name TEXT PRIMARY KEY, active INTEGER);
CREATE TABLE IF NOT EXISTS cluster_kv (key TEXT PRIMARY KEY, value INTEGER);
CREATE TABLE IF NOT EXISTS queue_metas_deleted (
  vhost TEXT, name TEXT, meta TEXT, PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS queue_msgs_deleted (
  vhost TEXT, queue TEXT, offset INTEGER, msg_id INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, offset)
);
CREATE TABLE IF NOT EXISTS queue_unacks_deleted (
  vhost TEXT, queue TEXT, msg_id INTEGER, offset INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, msg_id)
);
"""


class SqliteStore(StoreService):
    def __init__(self, path: str = ":memory:") -> None:
        self.path = path
        self._db: Optional[sqlite3.Connection] = None
        # single writer thread => strict FIFO op ordering
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="store")

    async def _exec(self, fn: Callable[[sqlite3.Connection], T]) -> T:
        loop = asyncio.get_running_loop()
        db = self._db
        assert db is not None, "store not opened"
        return await loop.run_in_executor(self._executor, lambda: fn(db))

    async def open(self) -> None:
        def _open() -> sqlite3.Connection:
            db = sqlite3.connect(self.path, check_same_thread=False)
            db.execute("PRAGMA journal_mode=WAL")
            db.execute("PRAGMA synchronous=NORMAL")
            db.executescript(_SCHEMA)
            db.commit()
            return db

        loop = asyncio.get_running_loop()
        self._db = await loop.run_in_executor(self._executor, _open)

    async def close(self) -> None:
        if self._db is not None:
            db = self._db
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(self._executor, db.close)
            self._db = None
        self._executor.shutdown(wait=False)

    # -- messages ---------------------------------------------------------

    async def insert_message(self, msg: StoredMessage) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO msgs VALUES (?,?,?,?,?,?,?)",
            (msg.id, msg.properties_raw, msg.body, msg.exchange,
             msg.routing_key, msg.refer_count, msg.ttl_ms),
        ).connection.commit())

    async def select_message(self, msg_id: int) -> Optional[StoredMessage]:
        def q(db: sqlite3.Connection):
            row = db.execute("SELECT * FROM msgs WHERE id=?", (msg_id,)).fetchone()
            return row

        row = await self._exec(q)
        if row is None:
            return None
        return StoredMessage(
            id=row[0], properties_raw=row[1], body=row[2], exchange=row[3],
            routing_key=row[4], refer_count=row[5], ttl_ms=row[6],
        )

    async def delete_message(self, msg_id: int) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM msgs WHERE id=?", (msg_id,)).connection.commit())

    async def update_message_refer_count(self, msg_id: int, count: int) -> None:
        await self._exec(lambda db: db.execute(
            "UPDATE msgs SET refer_count=? WHERE id=?", (count, msg_id)
        ).connection.commit())

    # -- queue meta -------------------------------------------------------

    async def insert_queue_meta(self, q: StoredQueue) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO queue_metas VALUES (?,?,?,?,?,?,?,?)",
            (q.vhost, q.name, int(q.durable), int(q.exclusive),
             int(q.auto_delete), q.ttl_ms, q.last_consumed,
             json.dumps(q.arguments)),
        ).connection.commit())

    async def select_queue(self, vhost: str, name: str) -> Optional[StoredQueue]:
        def q(db: sqlite3.Connection):
            meta = db.execute(
                "SELECT * FROM queue_metas WHERE vhost=? AND name=?",
                (vhost, name)).fetchone()
            if meta is None:
                return None
            msgs = db.execute(
                "SELECT offset, msg_id, body_size, expire_at_ms FROM queue_msgs "
                "WHERE vhost=? AND queue=? AND offset>? ORDER BY offset",
                (vhost, name, meta[6])).fetchall()
            unacks = db.execute(
                "SELECT msg_id, offset, body_size, expire_at_ms FROM queue_unacks "
                "WHERE vhost=? AND queue=?", (vhost, name)).fetchall()
            return meta, msgs, unacks

        out = await self._exec(q)
        if out is None:
            return None
        meta, msgs, unacks = out
        return StoredQueue(
            vhost=meta[0], name=meta[1], durable=bool(meta[2]),
            exclusive=bool(meta[3]), auto_delete=bool(meta[4]), ttl_ms=meta[5],
            last_consumed=meta[6], arguments=json.loads(meta[7] or "{}"),
            msgs=[tuple(m) for m in msgs],
            unacks={u[0]: (u[1], u[2], u[3]) for u in unacks},
        )

    async def all_queues(self, vhost: Optional[str] = None) -> list[StoredQueue]:
        def q(db: sqlite3.Connection):
            if vhost is None:
                return db.execute("SELECT vhost, name FROM queue_metas").fetchall()
            return db.execute(
                "SELECT vhost, name FROM queue_metas WHERE vhost=?", (vhost,)
            ).fetchall()

        names = await self._exec(q)
        out = []
        for vh, name in names:
            sq = await self.select_queue(vh, name)
            if sq:
                out.append(sq)
        return out

    # -- queue log --------------------------------------------------------

    async def insert_queue_msg(self, vhost, queue, offset, msg_id, body_size, expire_at_ms) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO queue_msgs VALUES (?,?,?,?,?,?)",
            (vhost, queue, offset, msg_id, body_size, expire_at_ms),
        ).connection.commit())

    async def delete_queue_msg(self, vhost, queue, offset) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM queue_msgs WHERE vhost=? AND queue=? AND offset=?",
            (vhost, queue, offset)).connection.commit())

    # -- watermark + unacks ------------------------------------------------

    async def update_queue_last_consumed(self, vhost, queue, last_consumed) -> None:
        def w(db: sqlite3.Connection):
            db.execute(
                "UPDATE queue_metas SET last_consumed=? WHERE vhost=? AND name=?",
                (last_consumed, vhost, queue))
            db.execute(
                "DELETE FROM queue_msgs WHERE vhost=? AND queue=? AND offset<=?",
                (vhost, queue, last_consumed))
            db.commit()

        await self._exec(w)

    async def insert_queue_unacks(self, vhost, queue, unacks) -> None:
        def w(db: sqlite3.Connection):
            db.executemany(
                "INSERT OR REPLACE INTO queue_unacks VALUES (?,?,?,?,?,?)",
                [(vhost, queue, m, o, s, e) for (m, o, s, e) in unacks])
            db.commit()

        await self._exec(w)

    async def delete_queue_unacks(self, vhost, queue, msg_ids) -> None:
        def w(db: sqlite3.Connection):
            db.executemany(
                "DELETE FROM queue_unacks WHERE vhost=? AND queue=? AND msg_id=?",
                [(vhost, queue, m) for m in msg_ids])
            db.commit()

        await self._exec(w)

    # -- delete/archive ----------------------------------------------------

    async def archive_queue(self, vhost, queue) -> None:
        def w(db: sqlite3.Connection):
            meta = db.execute(
                "SELECT * FROM queue_metas WHERE vhost=? AND name=?",
                (vhost, queue)).fetchone()
            if meta:
                db.execute(
                    "INSERT OR REPLACE INTO queue_metas_deleted VALUES (?,?,?)",
                    (vhost, queue, json.dumps(list(meta))))
            db.execute(
                "INSERT OR REPLACE INTO queue_msgs_deleted "
                "SELECT * FROM queue_msgs WHERE vhost=? AND queue=?",
                (vhost, queue))
            db.execute(
                "INSERT OR REPLACE INTO queue_unacks_deleted "
                "SELECT * FROM queue_unacks WHERE vhost=? AND queue=?",
                (vhost, queue))
            db.commit()

        await self._exec(w)

    async def delete_queue(self, vhost, queue) -> None:
        def w(db: sqlite3.Connection):
            db.execute("DELETE FROM queue_metas WHERE vhost=? AND name=?", (vhost, queue))
            db.execute("DELETE FROM queue_msgs WHERE vhost=? AND queue=?", (vhost, queue))
            db.execute("DELETE FROM queue_unacks WHERE vhost=? AND queue=?", (vhost, queue))
            db.commit()

        await self._exec(w)

    async def purge_queue_msgs(self, vhost, queue) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM queue_msgs WHERE vhost=? AND queue=?", (vhost, queue)
        ).connection.commit())

    # -- exchanges + binds -------------------------------------------------

    async def insert_exchange(self, ex: StoredExchange) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO exchanges VALUES (?,?,?,?,?,?,?)",
            (ex.vhost, ex.name, ex.type, int(ex.durable), int(ex.auto_delete),
             int(ex.internal), json.dumps(ex.arguments)),
        ).connection.commit())

    async def select_exchange(self, vhost, name) -> Optional[StoredExchange]:
        def q(db: sqlite3.Connection):
            row = db.execute(
                "SELECT * FROM exchanges WHERE vhost=? AND name=?",
                (vhost, name)).fetchone()
            if row is None:
                return None
            binds = db.execute(
                "SELECT routing_key, queue, arguments FROM binds "
                "WHERE vhost=? AND exchange=?", (vhost, name)).fetchall()
            return row, binds

        out = await self._exec(q)
        if out is None:
            return None
        row, binds = out
        return StoredExchange(
            vhost=row[0], name=row[1], type=row[2], durable=bool(row[3]),
            auto_delete=bool(row[4]), internal=bool(row[5]),
            arguments=json.loads(row[6] or "{}"),
            binds=[(b[0], b[1], json.loads(b[2]) if b[2] else None) for b in binds],
        )

    async def all_exchanges(self, vhost: Optional[str] = None) -> list[StoredExchange]:
        def q(db: sqlite3.Connection):
            if vhost is None:
                return db.execute("SELECT vhost, name FROM exchanges").fetchall()
            return db.execute(
                "SELECT vhost, name FROM exchanges WHERE vhost=?", (vhost,)
            ).fetchall()

        names = await self._exec(q)
        out = []
        for vh, name in names:
            ex = await self.select_exchange(vh, name)
            if ex:
                out.append(ex)
        return out

    async def delete_exchange(self, vhost, name) -> None:
        def w(db: sqlite3.Connection):
            db.execute("DELETE FROM exchanges WHERE vhost=? AND name=?", (vhost, name))
            db.execute("DELETE FROM binds WHERE vhost=? AND exchange=?", (vhost, name))
            db.commit()

        await self._exec(w)

    async def insert_bind(self, vhost, exchange, queue, routing_key, arguments) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO binds VALUES (?,?,?,?,?)",
            (vhost, exchange, queue, routing_key,
             json.dumps(arguments) if arguments else None),
        ).connection.commit())

    async def delete_bind(self, vhost, exchange, queue, routing_key) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM binds WHERE vhost=? AND exchange=? AND queue=? AND routing_key=?",
            (vhost, exchange, queue, routing_key)).connection.commit())

    async def delete_queue_binds(self, vhost, queue) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM binds WHERE vhost=? AND queue=?", (vhost, queue)
        ).connection.commit())

    async def allocate_worker_id(self) -> int:
        def w(db: sqlite3.Connection) -> int:
            # atomic across processes sharing the file: BEGIN IMMEDIATE takes
            # the write lock before the read-modify-write
            db.execute("BEGIN IMMEDIATE")
            try:
                db.execute(
                    "INSERT OR IGNORE INTO cluster_kv VALUES ('next_worker_id', 0)")
                db.execute(
                    "UPDATE cluster_kv SET value = value + 1 "
                    "WHERE key = 'next_worker_id'")
                row = db.execute(
                    "SELECT value FROM cluster_kv WHERE key = 'next_worker_id'"
                ).fetchone()
                db.commit()
                return int(row[0])
            except Exception:
                db.rollback()
                raise

        return await self._exec(w)

    # -- vhosts ------------------------------------------------------------

    async def insert_vhost(self, name: str, active: bool = True) -> None:
        await self._exec(lambda db: db.execute(
            "INSERT OR REPLACE INTO vhosts VALUES (?,?)", (name, int(active))
        ).connection.commit())

    async def all_vhosts(self) -> list[tuple[str, bool]]:
        rows = await self._exec(
            lambda db: db.execute("SELECT name, active FROM vhosts").fetchall())
        return [(r[0], bool(r[1])) for r in rows]

    async def delete_vhost(self, name: str) -> None:
        await self._exec(lambda db: db.execute(
            "DELETE FROM vhosts WHERE name=?", (name,)).connection.commit())
