"""Sampled end-to-end message tracing: fixed-slot spans per message.

A sampled publish mints a :class:`Trace` holding one slot per pipeline
stage (STAGES).  Stages stamp ``(start_ns, end_ns, node)`` tuples from
``time.perf_counter_ns()`` at the existing hot-path seams; nothing here
allocates per message unless the message was sampled.  Traces cross the
cluster planes as a compact blob appended *after* the record area of the
binary data-plane payloads (kinds 4/5/6) — old decoders iterate exactly
``count`` records and never look at trailing bytes, so the trailer is
wire-compatible in both directions.  The trailer is tail-anchored
(length + magic in the last 8 bytes) so a receiver can lift trace
contexts before the lazy record decoders consume the cursor.

Completed traces land in a bounded ring; traces slower than
``chana.mq.trace.slow-ms`` or touched by a chaos fire are additionally
kept in a slow ring so they survive churn (ISSUE 5: fault -> latency
causality must stay visible).
"""

from __future__ import annotations

import random
import struct
import time
from collections import OrderedDict, deque
from typing import Iterable, Optional, Sequence

from ..otel.context import W3CContext, derive_span_id
from ..otel.context import extract as _w3c_extract
from ..utils.metrics import Histogram, Metrics

# Fixed pipeline stages, one slot each.  Order is pipeline order; the
# indices are wire format (blob span tags), so append-only.
STAGES = (
    "ingress-parse",    # socket read -> frame/args/header parsed
    "route",            # exchange match / route-cache lookup
    "enqueue",          # fanout into queue ready lists
    "replicate-ship",   # staging into the replication log
    "cluster-push",     # batched in the data-plane accumulator
    "flush-wait",       # request in flight to the owner + response
    "remote-apply",     # owner-side decode + push_local
    "deliver",          # render + write toward the consumer
    "settle",           # ack/drop (or delivery for no-ack consumers)
    "intra-shard-hop",  # UDS hop between sibling shards on one node
    "wal-append",       # encode + buffer a WAL record (synchronous)
    "wal-commit",       # the group write+fsync that made it durable
    "flow-throttle",    # publish parked at the overload gate before run
)
INGRESS_PARSE = 0
ROUTE = 1
ENQUEUE = 2
REPLICATE_SHIP = 3
CLUSTER_PUSH = 4
FLUSH_WAIT = 5
REMOTE_APPLY = 6
DELIVER = 7
SETTLE = 8
INTRA_SHARD_HOP = 9
WAL_APPEND = 10
WAL_COMMIT = 11
FLOW_THROTTLE = 12

STAGE_KEYS = tuple("trace_" + s.replace("-", "_") + "_us" for s in STAGES)

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
TRAILER_MAGIC = 0x54524330  # "0CRT" on the wire, read back as TRC0


class Trace:
    __slots__ = ("trace_id", "origin", "slots", "chaos_rules", "finished",
                 "pending_ns", "w3c", "attrs")

    def __init__(self, trace_id: str, origin: str) -> None:
        self.trace_id = trace_id
        self.origin = origin
        self.slots: list = [None] * len(STAGES)
        self.chaos_rules: list = []
        self.finished = False
        # scratch timestamp used by the data plane between submit and flush
        self.pending_ns = 0
        # propagated W3C context (otel.context.W3CContext) — None unless
        # the publish carried a valid traceparent header
        self.w3c = None
        # routing attributes (exchange/queue/vhost/tenant), stamped at
        # enqueue time for sampled messages only; drives /admin/traces
        # filtering and the OTLP resource/span attributes
        self.attrs: "dict | None" = None

    def attr(self, key: str, value) -> None:
        a = self.attrs
        if a is None:
            a = self.attrs = {}
        if key not in a:
            a[key] = value

    def span(self, stage: int, start_ns: int, end_ns: int, node: str) -> None:
        self.slots[stage] = (start_ns, end_ns, node)

    def tag_chaos(self, rule: str) -> None:
        if rule not in self.chaos_rules:
            self.chaos_rules.append(rule)

    def merge(self, other: "Trace") -> None:
        """Fold spans from a revived wire copy into this (parked) trace."""
        for i, s in enumerate(other.slots):
            if s is not None and self.slots[i] is None:
                self.slots[i] = s
        for rule in other.chaos_rules:
            self.tag_chaos(rule)
        if self.w3c is None:
            self.w3c = other.w3c
        if other.attrs:
            for key, value in other.attrs.items():
                self.attr(key, value)

    @property
    def span_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def bounds_ns(self) -> "tuple[int, int] | None":
        starts = [s[0] for s in self.slots if s is not None]
        if not starts:
            return None
        return min(starts), max(s[1] for s in self.slots if s is not None)

    @property
    def total_us(self) -> float:
        b = self.bounds_ns()
        return (b[1] - b[0]) / 1000.0 if b else 0.0

    def to_dict(self) -> dict:
        b = self.bounds_ns()
        stages = {}
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            start_ns, end_ns, node = s
            stages[STAGES[i]] = {
                "offset_us": round((start_ns - b[0]) / 1000.0, 1),
                "dur_us": round((end_ns - start_ns) / 1000.0, 1),
                "node": node,
            }
        out = {
            "id": self.trace_id,
            "origin": self.origin,
            "total_us": round(self.total_us, 1),
            "spans": self.span_count,
            "nodes": sorted({s[2] for s in self.slots if s is not None}),
            "chaos_rules": list(self.chaos_rules),
            "stages": stages,
        }
        if self.w3c is not None:
            out["w3c"] = self.w3c.to_dict()
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out

    # -- wire blob: u8 ver | ss id | ss origin | u8 nrules | ss rule* |
    #    u8 nspans | (u8 stage | u64 t0 | u64 t1 | ss node)*
    #    v2 appends: u8 has_w3c | [ss tid | ss parent | ss root |
    #    u8 flags | ss tracestate] | u8 nattrs | (ss key | ss value)*
    #    Old decoders read exactly the v1 fields and ignore the tail, so
    #    v2 is forward-compatible inside a mixed-version cluster.
    def to_blob(self) -> bytes:
        parts = [b"\x02"]
        for text in (self.trace_id, self.origin):
            enc = text.encode("utf-8")[:255]
            parts.append(bytes((len(enc),)))
            parts.append(enc)
        rules = self.chaos_rules[:255]
        parts.append(bytes((len(rules),)))
        for rule in rules:
            enc = rule.encode("utf-8")[:255]
            parts.append(bytes((len(enc),)))
            parts.append(enc)
        spans = [(i, s) for i, s in enumerate(self.slots) if s is not None]
        parts.append(bytes((len(spans),)))
        for i, (t0, t1, node) in spans:
            enc = node.encode("utf-8")[:255]
            parts.append(bytes((i,)))
            parts.append(_U64.pack(t0))
            parts.append(_U64.pack(t1))
            parts.append(bytes((len(enc),)))
            parts.append(enc)
        w3c = self.w3c
        if w3c is None:
            parts.append(b"\x00")
        else:
            parts.append(b"\x01")
            for text in (w3c.trace_id, w3c.parent_span_id,
                         w3c.root_span_id, w3c.tracestate or ""):
                enc = text.encode("utf-8")[:255]
                parts.append(bytes((len(enc),)))
                parts.append(enc)
            parts.append(bytes((w3c.flags & 0xFF,)))
        attrs = list((self.attrs or {}).items())[:255]
        parts.append(bytes((len(attrs),)))
        for key, value in attrs:
            for text in (key, str(value)):
                enc = text.encode("utf-8")[:255]
                parts.append(bytes((len(enc),)))
                parts.append(enc)
        return b"".join(parts)

    @classmethod
    def from_blob(cls, blob) -> "Trace":
        view = memoryview(blob)
        version = view[0]
        pos = 1

        def ss():
            nonlocal pos
            n = view[pos]; pos += 1
            text = bytes(view[pos:pos + n]).decode("utf-8"); pos += n
            return text

        tr = cls(ss(), ss())
        nrules = view[pos]; pos += 1
        for _ in range(nrules):
            tr.chaos_rules.append(ss())
        nspans = view[pos]; pos += 1
        for _ in range(nspans):
            stage = view[pos]; pos += 1
            t0 = _U64.unpack_from(view, pos)[0]; pos += 8
            t1 = _U64.unpack_from(view, pos)[0]; pos += 8
            node = ss()
            if stage < len(STAGES):
                tr.slots[stage] = (t0, t1, node)
        if version >= 2 and pos < len(view):
            if view[pos]:  # has_w3c flag (the byte itself consumed below)
                pos += 1
                tid, parent, root, state = ss(), ss(), ss(), ss()
                flags = view[pos]; pos += 1
                tr.w3c = W3CContext(tid, parent, root, flags=flags,
                                    tracestate=state or None)
            else:
                pos += 1
            nattrs = view[pos]; pos += 1
            for _ in range(nattrs):
                key = ss()
                tr.attr(key, ss())
        return tr


def encode_trailer(entries: Sequence["tuple[int, Trace]"]) -> bytes:
    """Trace contexts for records ``idx`` of a data-plane payload.

    Layout: ``u16 n | (u32 idx | u16 blob_len | blob)* | u32 body_len |
    u32 magic`` — the last 8 bytes let a receiver find the trailer from
    the payload tail without walking the records first.
    """
    parts = [_U16.pack(len(entries))]
    for idx, tr in entries:
        blob = tr.to_blob()
        parts.append(_U32.pack(idx))
        parts.append(_U16.pack(len(blob)))
        parts.append(blob)
    body = b"".join(parts)
    return body + _U32.pack(len(body)) + _U32.pack(TRAILER_MAGIC)


def decode_trailer(payload) -> "dict[int, Trace] | None":
    """Lift {record_idx: Trace} off a payload tail; None if absent."""
    view = memoryview(payload)
    total = len(view)
    if total < 10:
        return None
    try:
        if _U32.unpack_from(view, total - 4)[0] != TRAILER_MAGIC:
            return None
        blen = _U32.unpack_from(view, total - 8)[0]
        if blen < 2 or blen > total - 8:
            return None
        body = view[total - 8 - blen: total - 8]
        count = _U16.unpack_from(body, 0)[0]
        pos = 2
        out: "dict[int, Trace]" = {}
        for _ in range(count):
            idx = _U32.unpack_from(body, pos)[0]; pos += 4
            n = _U16.unpack_from(body, pos)[0]; pos += 2
            out[idx] = Trace.from_blob(body[pos:pos + n]); pos += n
        return out
    except (struct.error, IndexError, UnicodeDecodeError, ValueError):
        return None  # accidental magic match in an untraced payload


class TraceRuntime:
    """Sampling, span accounting, ring buffers, and cross-node stitching.

    Installed as the module-global ``trace.ACTIVE`` (same gating idiom as
    chaos): disabled means every seam is one module-attribute load plus
    an ``is None`` check.  The sampling RNG is seeded (defaulting to the
    chaos seed when a plan is installed) and consumes exactly one uniform
    draw per publish, so the sampled subset is deterministic for a given
    seed regardless of the sample rate.
    """

    def __init__(self, sample_rate: float = 0.01, ring_size: int = 256,
                 slow_ms: float = 250.0, metrics: Optional[Metrics] = None,
                 seed: int = 0, node: str = "local") -> None:
        self.rate = float(sample_rate)
        self.ring_size = int(ring_size)
        self.slow_ms = float(slow_ms)
        self.metrics = metrics
        self.node = node
        self.seed = seed
        self._rng = random.Random(seed)
        self._seq = 0
        # forced (W3C-propagated) samples number their own sequence and
        # never touch _rng/_seq: a headerless run stays draw-for-draw and
        # id-for-id identical whether or not this path exists
        self._wseq = 0
        # set by the OTLP exporter: called with each trace finish() lands
        # in the ring, off the per-message hot path
        self.export_hook = None
        # trace attached to the publish currently being processed; only
        # set/cleared around synchronous sections (never held across await)
        self.current: Optional[Trace] = None
        # stamped by the connection read loop; begin_publish discards it
        # when stale (previous chunk, idle connection)
        self.ingress_ns = 0
        # (t0, t1) stamped by a connection releasing held publishes; the
        # first sampled publish after the release carries the span, then
        # it is consumed (one park episode -> one flow-throttle span)
        self.flow_ns: Optional[tuple] = None
        self.ring: deque = deque(maxlen=self.ring_size)
        self.slow: deque = deque(maxlen=self.ring_size)
        self._inflight: "OrderedDict[str, Trace]" = OrderedDict()
        self._inflight_cap = max(4 * self.ring_size, 64)
        self._recent_fires: deque = deque(maxlen=64)
        if metrics is not None:
            for key in STAGE_KEYS:
                metrics.trace_stage_us.setdefault(key, Histogram())

    # -- sampling ----------------------------------------------------------
    def sample(self) -> bool:
        return self._rng.random() < self.rate

    def begin_publish(self, node: Optional[str] = None,
                      headers: Optional[dict] = None) -> Optional[Trace]:
        """One uniform draw; mint + stamp ingress-parse when sampled.

        Always (re)sets ``current`` so a previous publish's trace can
        never leak onto the next message.

        A valid ``traceparent`` in ``headers`` force-samples on a
        SEPARATE path that skips the draw entirely: the seeded sampling
        sequence (and the ``node#seq`` ids it mints) stays byte-identical
        for every publish that does not carry a context, which is what
        the same-seed soak determinism gates compare. A malformed header
        falls through to the normal seeded path without breaking the
        publish (W3C: restart the trace).
        """
        if headers is not None:
            ctx = _w3c_extract(headers)
            if ctx is not None:
                return self._begin_forced(node, ctx)
        if self._rng.random() >= self.rate:
            self.current = None
            return None
        node = node or self.node
        self._seq += 1
        tr = Trace(f"{node}#{self._seq}", node)
        self._stamp_ingress(tr, node)
        self.current = tr
        if self.metrics is not None:
            self.metrics.trace_sampled += 1
        return tr

    def _begin_forced(self, node: Optional[str], ctx: tuple) -> Trace:
        """Mint a force-sampled trace for a propagated W3C context.

        Ids are derived (otel.context), never drawn, and the forced
        sequence counter is separate from the seeded one — see
        begin_publish for why."""
        node = node or self.node
        tid, parent, flags, state = ctx
        self._wseq += 1
        tr = Trace(f"{node}#w{self._wseq}", node)
        tr.w3c = W3CContext(
            tid, parent,
            derive_span_id(tid, parent, node, str(self._wseq)),
            flags=flags | 0x01, tracestate=state)
        self._stamp_ingress(tr, node)
        self.current = tr
        if self.metrics is not None:
            self.metrics.trace_sampled += 1
            self.metrics.otel_forced_samples += 1
        return tr

    def begin_remote(self, ctx: tuple, node: Optional[str] = None,
                     attrs: Optional[dict] = None) -> Trace:
        """Force-sampled trace for a context that arrived INSIDE shipped
        data rather than on a live publish (federation segment apply):
        no ingress window to stamp, the caller owns the stage spans."""
        node = node or self.node
        tid, parent, flags, state = ctx
        self._wseq += 1
        tr = Trace(f"{node}#w{self._wseq}", node)
        tr.w3c = W3CContext(
            tid, parent,
            derive_span_id(tid, parent, node, str(self._wseq)),
            flags=flags | 0x01, tracestate=state)
        if attrs:
            tr.attrs = dict(attrs)
        if self.metrics is not None:
            self.metrics.trace_sampled += 1
            self.metrics.otel_forced_samples += 1
        return tr

    def _stamp_ingress(self, tr: Trace, node: str) -> None:
        now = time.perf_counter_ns()
        t0 = self.ingress_ns
        if not t0 or t0 > now or now - t0 > 50_000_000:
            t0 = now  # stale stamp: connection idle or different conn
        tr.span(INGRESS_PARSE, t0, now, node)
        flow = self.flow_ns
        if flow is not None:
            self.flow_ns = None
            f0, f1 = flow
            if f1 <= now and now - f1 <= 50_000_000:
                # same staleness bound as ingress: the span belongs to the
                # publish stream released just now, not an old episode
                tr.span(FLOW_THROTTLE, f0, f1, node)

    # -- cross-node bookkeeping -------------------------------------------
    def park(self, tr: Trace) -> None:
        """Keep an origin-side trace while it rides the data plane."""
        inf = self._inflight
        inf[tr.trace_id] = tr
        if len(inf) > self._inflight_cap:
            inf.popitem(last=False)
            if self.metrics is not None:
                self.metrics.trace_evicted += 1

    def adopt(self, tr: Trace) -> Trace:
        """Merge a revived wire copy with its parked origin half.

        The parked entry stays inflight until finish() — in-process
        multi-node runs share one runtime and adopt the same id from the
        push AND the deliver plane; popping on first adopt would fork the
        deliver-side spans onto a disconnected copy."""
        parked = self._inflight.get(tr.trace_id)
        if parked is not None and parked is not tr:
            parked.merge(tr)
            return parked
        return tr

    # -- chaos correlation -------------------------------------------------
    def note_chaos_fire(self, rule: str) -> None:
        self._recent_fires.append((time.perf_counter_ns(), rule))
        cur = self.current
        if cur is not None:
            cur.tag_chaos(rule)

    # -- completion --------------------------------------------------------
    def on_settle(self, tr: Trace, node: Optional[str] = None) -> None:
        if tr.finished:
            return
        now = time.perf_counter_ns()
        d = tr.slots[DELIVER]
        start = d[1] if d is not None else now
        tr.span(SETTLE, start, now, node or self.node)
        self.finish(tr)

    def finish(self, tr: Trace) -> None:
        if tr.finished:
            return
        tr.finished = True
        self._inflight.pop(tr.trace_id, None)
        b = tr.bounds_ns()
        if b is None:
            return
        lo, hi = b
        total_us = (hi - lo) / 1000.0
        m = self.metrics
        if m is not None:
            m.trace_completed += 1
            stage_hs = m.trace_stage_us
            for i, s in enumerate(tr.slots):
                if s is None:
                    continue
                h = stage_hs.get(STAGE_KEYS[i])
                if h is not None:
                    h.observe_us(max(0.0, (s[1] - s[0]) / 1000.0))
        # chaos fires inside the trace window tag it even if the fire
        # happened off the publish path (e.g. a data-plane send seam)
        for fire_ns, rule in self._recent_fires:
            if lo <= fire_ns <= hi:
                tr.tag_chaos(rule)
        self.ring.append(tr)
        slow = total_us >= self.slow_ms * 1000.0
        if slow or tr.chaos_rules:
            self.slow.append(tr)
            if m is not None:
                if slow:
                    m.trace_slow += 1
                if tr.chaos_rules:
                    m.trace_chaos_tagged += 1
        hook = self.export_hook
        if hook is not None:
            try:
                hook(tr)
            except Exception:  # pragma: no cover - exporter bug
                # span export must never break message completion
                self.export_hook = None

    # -- inspection --------------------------------------------------------
    def find(self, trace_id: str) -> Optional[Trace]:
        # prefer the copy with the most spans: in-process multi-node runs
        # share one runtime and may finalize a partial owner-side view too
        best: Optional[Trace] = None
        pools: "Iterable[Iterable[Trace]]" = (
            self.slow, self.ring, self._inflight.values())
        for pool in pools:
            for tr in pool:
                if tr.trace_id == trace_id:
                    if best is None or tr.span_count > best.span_count:
                        best = tr
        return best

    def query(self, *, queue: Optional[str] = None,
              exchange: Optional[str] = None, vhost: Optional[str] = None,
              tenant: Optional[str] = None, stage: Optional[str] = None,
              min_duration_us: float = 0, limit: int = 50) -> "list[Trace]":
        """Filtered view over the completed rings (slow first, then
        recent), newest first, deduped by id — the /admin/traces query
        layer. ``queue`` matches any member of the comma-joined queue
        attr (a fanout lands in several); ``stage`` requires the named
        stage slot to be populated."""
        stage_idx = STAGES.index(stage) if stage in STAGES else None
        out: "list[Trace]" = []
        seen: set = set()
        for pool in (self.slow, self.ring):
            for tr in reversed(pool):
                if tr.trace_id in seen:
                    continue
                seen.add(tr.trace_id)
                a = tr.attrs or {}
                if exchange is not None and a.get("exchange") != exchange:
                    continue
                if vhost is not None and a.get("vhost") != vhost:
                    continue
                if tenant is not None and a.get("tenant") != tenant:
                    continue
                if queue is not None and \
                        queue not in (a.get("queue") or "").split(","):
                    continue
                if stage is not None and (
                        stage_idx is None or tr.slots[stage_idx] is None):
                    continue
                if min_duration_us and tr.total_us < min_duration_us:
                    continue
                out.append(tr)
                if len(out) >= limit:
                    return out
        return out

    def status(self, limit: int = 20) -> dict:
        return {
            "node": self.node,
            "sample_rate": self.rate,
            "ring_size": self.ring_size,
            "slow_ms": self.slow_ms,
            "seed": self.seed,
            "sampled": self._seq,
            "forced": self._wseq,
            "completed_in_ring": len(self.ring),
            "inflight": len(self._inflight),
            "recent": [t.to_dict() for t in list(self.ring)[-limit:]],
            "slow": [t.to_dict() for t in list(self.slow)[-limit:]],
        }
