"""Shared and key-shared consumer groups on stream queues.

Pulsar-style subscription semantics grafted onto the stream cursor
machinery: consumers that pass ``x-group: <name>`` at consume time join
ONE group cursor instead of getting a private replay cursor. The group
reads the log once and spreads records across its members:

- ``x-group-type: shared`` (default) — round-robin across members with
  available QoS credit. No ordering guarantee beyond the log itself;
  maximum drain parallelism.
- ``x-group-type: key-shared`` — each record's routing key hashes onto a
  consistent-hash ring of members, and a key STICKS to the member that
  holds its in-flight deliveries: while any delivery for key K is
  unacked, every further K record goes to (or waits for) that member.
  Per-key delivery order is therefore preserved across acks, nacks with
  requeue, and member churn; keys only migrate between members when the
  key has nothing in flight.

Progress is a single committed offset per group (the contiguous floor
below every in-flight and pending-redelivery record), persisted through
the queue's existing cursor-commit machinery under the reserved name
``%grp%<group>`` — so a group survives broker restarts and full member
churn exactly like an individual durable cursor.

Redelivery: a member leaving (cancel, channel close, connection drop)
moves its in-flight offsets into an offset-ordered redelivery heap that
is drained BEFORE the group reads new records — combined with key
stickiness this keeps per-key order intact through mid-flight
disconnects (the chaos soak asserts exactly this invariant).

Like Pulsar, an individual negative-ack redelivery (as opposed to a
member leaving) may arrive after later records already delivered to the
same member; that is the one place per-key order is relaxed.
"""

from __future__ import annotations

import hashlib
import heapq
from bisect import bisect_right
from typing import TYPE_CHECKING, Any, Optional

from ..broker.entities import QueuedMessage

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.channel import Consumer
    from .queue import StreamQueue

# committed-offset namespace for group cursors ("%" is not producible by
# client consumer tags the broker generates, and collides with nothing:
# individual cursors commit under their consumer tag, gets under "%get%")
GROUP_CURSOR_PREFIX = "%grp%"

GROUP_MODES = ("shared", "key-shared")

# virtual nodes per member on the key-shared ring: enough to keep key
# spread within a few percent of uniform at small member counts
_VNODES = 32


def validate_group_args(queue, arguments: Optional[dict]) -> Optional[str]:
    """Consume-time validation of ``x-group`` / ``x-group-type``; returns
    an error string (PRECONDITION_FAILED) or None. Called before
    ConsumeOk so a bad subscription never half-attaches."""
    args = arguments or {}
    name = args.get("x-group")
    mode = args.get("x-group-type")
    if name is None:
        if mode is not None:
            return "x-group-type requires x-group"
        return None
    if not isinstance(name, str) or not name:
        return "x-group must be a non-empty string"
    if mode is None:
        mode = "shared"
    elif mode not in GROUP_MODES:
        return f"unknown x-group-type {mode!r} (shared/key-shared)"
    existing = queue._groups.get(name)
    if existing is not None and existing.mode != mode:
        return (f"group '{name}' already exists with "
                f"x-group-type {existing.mode}")
    return None


def _ring_points(tag: str) -> list[int]:
    points = []
    for vn in range(_VNODES):
        digest = hashlib.sha1(f"{tag}#{vn}".encode()).digest()
        points.append(int.from_bytes(digest[:8], "big"))
    return points


def _key_point(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class StreamGroup:
    """One named subscription on a stream queue: a shared read position,
    its member set, in-flight tracking, and the redelivery heap."""

    __slots__ = (
        "queue", "name", "mode", "cursor_name", "members", "next",
        "skip_ts_ms", "_inflight", "_redeliver", "_redeliver_set",
        "_order", "_rr", "_key_owner", "_key_inflight", "_ring",
    )

    def __init__(self, queue: "StreamQueue", name: str, mode: str) -> None:
        self.queue = queue
        self.name = name
        self.mode = mode
        self.cursor_name = GROUP_CURSOR_PREFIX + name
        self.members: dict[str, "Consumer"] = {}
        self.next = 0  # seeded by StreamQueue.add_consumer on first join
        self.skip_ts_ms: Optional[int] = None
        # offset -> (member_tag, routing_key) for every unacked delivery
        self._inflight: dict[int, tuple[str, str]] = {}
        # offsets awaiting redelivery, drained in offset order before any
        # fresh read — the per-key-order keystone on member loss
        self._redeliver: list[int] = []
        self._redeliver_set: set[int] = set()
        # member join order (round-robin base for shared mode)
        self._order: list[str] = []
        self._rr = 0
        # key-shared state: sticky owner while the key has deliveries in
        # flight, consistent-hash ring for free keys
        self._key_owner: dict[str, str] = {}
        self._key_inflight: dict[str, int] = {}
        self._ring: list[tuple[int, str]] = []

    # -- membership --------------------------------------------------------

    def add_member(self, consumer: "Consumer") -> None:
        self.members[consumer.tag] = consumer
        self._order.append(consumer.tag)
        if self.mode == "key-shared":
            self._rebuild_ring()

    def remove_member(self, tag: str) -> None:
        """Member departed. Channel teardown requeues its unacked BEFORE
        removing consumers, so on disconnect nothing is in flight here by
        now; after a bare basic.cancel the client may still settle its
        outstanding tags, so in-flight entries are left to drain through
        the normal ack/requeue paths (keys stay stuck to the departed tag
        until then — _owner_for blocks them rather than reassigning, which
        is what preserves per-key order through a cancel)."""
        self.members.pop(tag, None)
        try:
            self._order.remove(tag)
        except ValueError:
            pass
        if self._rr >= len(self._order):
            self._rr = 0
        if self.mode == "key-shared":
            self._rebuild_ring()
        self._maybe_release_tag(tag)
        if self.members:
            self.queue.schedule_dispatch()

    def _maybe_release_tag(self, tag: str) -> None:
        """Drop the queue's tag->group settle route once a departed
        member's last in-flight delivery settles (guarded: the tag may
        have been reused by a new consumer, possibly in another group)."""
        if tag in self.members:
            return
        if any(t == tag for t, _ in self._inflight.values()):
            return
        routes = self.queue._member_groups
        if routes.get(tag) is self:
            del routes[tag]

    def _rebuild_ring(self) -> None:
        ring: list[tuple[int, str]] = []
        for tag in self.members:
            ring.extend((p, tag) for p in _ring_points(tag))
        ring.sort()
        self._ring = ring

    def _owner_for(self, key: str) -> Optional["Consumer"]:
        tag = self._key_owner.get(key)
        if tag is not None:
            # sticky while the key has in-flight deliveries; a departed
            # owner returns None → the key BLOCKS until those settle (the
            # alternative, reassigning immediately, would let a new member
            # see later records before the old one's requeue resolves)
            return self.members.get(tag)
        if not self._ring:
            return None
        points = [p for p, _ in self._ring]
        idx = bisect_right(points, _key_point(key)) % len(self._ring)
        return self.members.get(self._ring[idx][1])

    # -- dispatch ----------------------------------------------------------

    def dispatch(self, budget: int) -> bool:
        """One pass: drain the redelivery heap in offset order, then read
        fresh records at the group position, handing each to a member. A
        record whose target member has no credit parks the whole group
        (head-of-line) — skipping past it would break per-key order and
        tear a hole in the committed floor. Returns True when the budget
        (not credit or the tail) stopped the pass."""
        queue = self.queue
        if not self.members:
            return False
        from .queue import _COMPACTED, _LOADING  # sentinels

        metrics = queue.broker.metrics
        delivered = 0
        while delivered < budget:
            if self._redeliver:
                offset = self._redeliver[0]
                redelivered = True
            else:
                if self.next < queue.first_offset:
                    self.next = queue.first_offset
                offset = self.next
                redelivered = False
            rec = queue._record_at(offset)
            if rec is _LOADING:
                break  # blob fetch kicked; resume next pass
            if rec is None or rec is _COMPACTED:
                if redelivered:
                    # retention or compaction removed the record while it
                    # waited: nothing left to redeliver
                    heapq.heappop(self._redeliver)
                    self._redeliver_set.discard(offset)
                    self._commit_floor()
                    continue
                if rec is _COMPACTED:
                    self.next = offset + 1
                    continue
                break  # log tail
            if not redelivered and self.skip_ts_ms is not None:
                if rec.ts_ms < self.skip_ts_ms:
                    self.next = offset + 1
                    continue
                self.skip_ts_ms = None
            key = rec.routing_key
            consumer = self._pick_member(key, len(rec.body))
            if consumer is None:
                break  # no credit anywhere / key owner saturated
            qm = QueuedMessage(queue._record_message(rec), rec.offset,
                               None, body_size=len(rec.body))
            qm.redelivered = redelivered
            delivery = consumer.deliver(queue, qm)
            metrics.stream_records_delivered += 1
            metrics.stream_group_deliveries += 1
            queue.n_delivered += 1
            if redelivered:
                heapq.heappop(self._redeliver)
                self._redeliver_set.discard(offset)
            else:
                self.next = offset + 1
            delivered += 1
            if delivery is None:  # no_ack member: settled at delivery
                self._commit_floor()
                queue.broker.unrefer(qm.message)
            else:
                self._inflight[offset] = (consumer.tag, key)
                if self.mode == "key-shared":
                    self._key_inflight[key] = (
                        self._key_inflight.get(key, 0) + 1)
                    self._key_owner[key] = consumer.tag
                queue.outstanding[(consumer.tag, offset)] = delivery
                if queue._counted:
                    queue.broker.queue_unacked += 1
        return delivered >= budget

    def _pick_member(self, key: str, size: int) -> Optional["Consumer"]:
        if self.mode == "key-shared":
            owner = self._owner_for(key)
            if owner is None or not owner.can_take(size):
                return None  # head-of-line: preserves per-key order
            return owner
        # shared: round-robin from the cursor, first member with credit
        n = len(self._order)
        for i in range(n):
            tag = self._order[(self._rr + i) % n]
            member = self.members.get(tag)
            if member is not None and member.can_take(size):
                self._rr = (self._rr + i + 1) % n
                return member
        return None

    # -- settlement --------------------------------------------------------

    def settle(self, offset: int) -> None:
        """ack / reject-without-requeue: the record is done; advance the
        committed floor past any contiguous completed prefix."""
        entry = self._inflight.pop(offset, None)
        if entry is not None:
            self._unstick(entry[1])
            self._maybe_release_tag(entry[0])
        self._commit_floor()

    def requeue(self, tag: str, offset: int) -> None:
        """nack-with-requeue or teardown release: back onto the heap for
        the next dispatch pass (possibly to a different member)."""
        entry = self._inflight.pop(offset, None)
        if entry is None:
            return
        self._unstick(entry[1])
        if offset not in self._redeliver_set:
            heapq.heappush(self._redeliver, offset)
            self._redeliver_set.add(offset)
        self._maybe_release_tag(entry[0])

    def _unstick(self, key: str) -> None:
        if self.mode != "key-shared":
            return
        n = self._key_inflight.get(key, 0) - 1
        if n <= 0:
            self._key_inflight.pop(key, None)
            self._key_owner.pop(key, None)  # key free: ring may reassign
        else:
            self._key_inflight[key] = n

    def _commit_floor(self) -> None:
        """Commit the offset below which everything is settled: in-flight
        and pending-redelivery records hold the floor down, so a crash or
        restart redelivers exactly the unsettled suffix."""
        floor = self.next
        if self._inflight:
            floor = min(floor, min(self._inflight))
        if self._redeliver:
            floor = min(floor, self._redeliver[0])
        if floor > 0:
            self.queue._commit(self.cursor_name, floor - 1)

    # -- introspection (admin surface) ------------------------------------

    def snapshot(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "mode": self.mode,
            "members": len(self.members),
            "next_offset": self.next,
            "committed": self.queue.committed.get(self.cursor_name),
            "inflight": len(self._inflight),
            "redeliver_pending": len(self._redeliver),
            "sticky_keys": len(self._key_owner),
        }
