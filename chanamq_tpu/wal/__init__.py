"""Log-structured write-ahead storage engine (ISSUE 8).

``WalStore`` wraps the SQLite store: appends win durability via one
cross-channel group fsync per flush window, SQLite stays the read index
fed by a background checkpointer, and recovery replays the WAL tail.
See :mod:`chanamq_tpu.wal.engine` for the full design notes.
"""

from .engine import CHECKPOINT_KEY, WalStore  # noqa: F401
