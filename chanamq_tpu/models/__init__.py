"""Auxiliary JAX models — analytics over broker metrics.

The reference contains no ML compute path (SURVEY.md preamble: zero tensor
code in the tree), so per SURVEY.md §7.1 the only honest JAX component is
batch analytics over broker telemetry, strictly OFF the message path. The
flagship model is a small causal transformer that forecasts per-queue
traffic (enqueue/dequeue rates, depth) from a sliding window of metrics —
the kind of capacity/backlog prediction an operator would bolt onto a broker.

Live wiring (models/telemetry.py + models/service.py): a sampler task on
the broker's event loop feeds a telemetry ring from utils.metrics; a worker
thread trains/predicts off-path; the admin API serves GET /admin/forecast
and chanamq_forecast_* Prometheus gauges. Enable with
chana.mq.forecast.enabled.

TPU-first by construction: bfloat16 matmuls sized for the MXU, static
shapes, lax.scan-free forward, shardable over a (dp, tp) device mesh via
NamedSharding annotations (see chanamq_tpu.parallel).

Lazy attribute access: importing this package must NOT import jax — the
broker imports models.service/models.telemetry (numpy-only) on its event
loop, and forecaster.py pulls jax at module top. The jax import happens
only when a forecaster symbol is first touched (the service does that on
its worker thread).
"""

_FORECASTER_SYMBOLS = (
    "ForecasterConfig",
    "init_params",
    "forward",
    "loss_fn",
    "make_train_step",
    "init_momentum",
    "synthetic_batch",
)

__all__ = list(_FORECASTER_SYMBOLS)


def __getattr__(name: str):
    if name in _FORECASTER_SYMBOLS:
        from . import forecaster

        return getattr(forecaster, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
