"""Cluster interconnect fast-path tests: binary codec roundtrips, data
stream request/response, frame_too_large resync, reconnect backoff,
per-call timeouts, push_many partial failure, and settle-batching ordering
vs. redelivery (zero loss / zero duplication in ack mode)."""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster import dataplane as dp
from chanamq_tpu.cluster.rpc import (
    KIND_DREQUEST,
    MAX_FRAME,
    RpcClient,
    RpcError,
    RpcServer,
    RpcTimeout,
    encode_data_frame,
)

from test_cluster_broker import owner_and_other, start_cluster

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

async def test_push_many_codec_roundtrip_zero_copy():
    props = PERSISTENT.encode_header(5)
    parts = []
    parts.extend(dp.encode_push_record(
        "/", ["q1", "q2"], "ex", "rk", props, b"body1"))
    parts.extend(dp.encode_push_record(
        "vh", ["q3"], "", "q3", props, b"body2xx"))
    frame = b"".join([dp._U32.pack(2), *parts])
    view = memoryview(frame)
    records = list(dp.decode_push_many(view))
    assert len(records) == 2
    vhost, queues, exchange, rk, props_v, body_v = records[0]
    assert (vhost, queues, exchange, rk) == ("/", ["q1", "q2"], "ex", "rk")
    assert bytes(props_v) == props
    assert bytes(body_v) == b"body1"
    # zero-copy: the body view slices the frame buffer, no new bytes object
    assert isinstance(body_v, memoryview) and body_v.obj is frame
    vhost, queues, exchange, rk, props_v, body_v = records[1]
    assert (vhost, queues, exchange, rk) == ("vh", ["q3"], "", "q3")
    assert bytes(body_v) == b"body2xx"


async def test_settle_many_codec_roundtrip():
    entries = [
        ("/", "qa", "ack", "tag1", 3, [1, 2, 3]),
        ("/", "qb", "requeue", "", 0, [10]),
        ("vh", "qc", "drop", "tag2", 1, []),
    ]
    frame = b"".join([dp._U32.pack(len(entries))] + [
        dp.encode_settle_entry(*e) for e in entries])
    assert list(dp.decode_settle_many(memoryview(frame))) == [
        (v, q, op, t, c, o) for v, q, op, t, c, o in entries]


async def test_deliver_many_codec_roundtrip():
    props = BasicProperties().encode_header(3)
    records = []
    records.extend(dp.encode_deliver_record(
        7, True, 1234, 999_000, "ex", "rk", props, b"abc"))
    records.extend(dp.encode_deliver_record(
        8, False, 1235, None, "", "q", props, b""))
    frame = b"".join(
        [dp.encode_deliver_head("/", "dq", "ctag", 2), *records])
    vhost, queue, tag, it = dp.decode_deliver_many(memoryview(frame))
    assert (vhost, queue, tag) == ("/", "dq", "ctag")
    decoded = list(it)
    off, redel, mid, exp, ex, rk, props_v, body_v = decoded[0]
    assert (off, redel, mid, exp, ex, rk) == (7, True, 1234, 999_000, "ex", "rk")
    assert bytes(body_v) == b"abc" and bytes(props_v) == props
    off, redel, mid, exp, ex, rk, props_v, body_v = decoded[1]
    assert (off, redel, mid, exp, ex, rk) == (8, False, 1235, None, "", "q")
    assert bytes(body_v) == b""


# ---------------------------------------------------------------------------
# data stream + rpc hardening
# ---------------------------------------------------------------------------

async def test_data_stream_request_roundtrip_and_remote_error():
    server = RpcServer("127.0.0.1", 0)

    async def echo(view):
        return [b"echo:", bytes(view)]

    async def boom(view):
        raise RpcError("nope", "handler refused")

    server.register_binary(1, echo)
    server.register_binary(2, boom)
    await server.start()
    stream = dp.DataStream("127.0.0.1", server.bound_port)
    try:
        reply = await stream.request(1, [b"pay", b"load"])
        assert bytes(reply) == b"echo:payload"
        with pytest.raises(RpcError) as err:
            await stream.request(2, [b"x"])
        assert "handler refused" in str(err.value)
        # the error reply leaves the stream usable (no reconnect needed)
        assert bytes(await stream.request(1, [b"ok"])) == b"echo:ok"
    finally:
        await stream.close()
        await server.stop()


async def test_frame_too_large_closes_connection_then_recovers():
    server = RpcServer("127.0.0.1", 0)

    async def ping(payload):
        return {"pong": True}

    server.register("ping", ping)
    await server.start()
    try:
        # a raw peer announces an impossible frame: the server must drop
        # the connection (the stream can't be re-synced mid-frame)
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.bound_port)
        import struct
        writer.write(struct.pack(">I", MAX_FRAME + 1))
        await writer.drain()
        assert await reader.read(64) == b""  # server closed on us
        writer.close()
        # the listener itself survives: a well-behaved client still works
        client = RpcClient("127.0.0.1", server.bound_port)
        assert (await client.call("ping", {}))["pong"] is True
        await client.close()
    finally:
        await server.stop()


async def test_client_per_call_timeout():
    server = RpcServer("127.0.0.1", 0)

    async def slow(payload):
        await asyncio.sleep(30)
        return {}

    server.register("slow", slow)
    await server.start()
    client = RpcClient("127.0.0.1", server.bound_port, timeout_s=30)
    try:
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        with pytest.raises(RpcTimeout):
            await client.call("slow", {}, timeout_s=0.2)
        assert loop.time() - t0 < 5  # per-call override, not the 30s default
    finally:
        await client.close()
        await server.stop()


async def test_reconnect_backoff_fails_fast():
    # grab a port with nothing listening on it
    probe = await asyncio.start_server(lambda r, w: None, "127.0.0.1", 0)
    dead_port = probe.sockets[0].getsockname()[1]
    probe.close()
    await probe.wait_closed()

    client = RpcClient("127.0.0.1", dead_port, connect_timeout_s=0.5)
    with pytest.raises((RpcError, OSError)):
        await client.call("anything", {}, timeout_s=1)
    # backoff armed: the next attempt fails immediately, no dial
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    with pytest.raises(RpcError) as err:
        await client.call("anything", {}, timeout_s=1)
    assert err.value.code == "backoff"
    assert loop.time() - t0 < 0.05
    await client.close()


# ---------------------------------------------------------------------------
# cluster-level contracts
# ---------------------------------------------------------------------------

async def test_push_many_partial_failure_keeps_rest(tmp_path):
    """One missing queue inside a data-plane batch must not drop or
    duplicate the other pushes riding the same micro-batch."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "pf_ok")
        client = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await client.channel()
        await ch.queue_declare("pf_ok", durable=True)

        props = PERSISTENT.encode_header(2)
        records = [
            (owner.name, ("/", ["pf_ok"], "", "pf_ok", props, b"m1")),
            # routed to a queue nobody ever declared: skipped on the owner
            (owner.name, ("/", ["pf_gone"], "", "pf_gone", props, b"mX")),
            (owner.name, ("/", ["pf_ok"], "", "pf_ok", props, b"m2")),
        ]
        failures = await other.cluster.push_batch(records)
        assert failures == []
        await asyncio.sleep(0.2)
        queue = owner.server.broker.vhosts["/"].queues["pf_ok"]
        assert [bytes(qm.message.body) for qm in queue.messages] == [b"m1", b"m2"]
        await client.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_settle_batch_ordering_vs_redelivery(tmp_path):
    """Acks buffered in the settle window must be applied on the owner
    before a consumer cancel requeues outstanding deliveries: the acked
    half never redelivers, the unacked half redelivers exactly once."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "sb_q")
        client = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await client.channel()
        await ch.queue_declare("sb_q", durable=True)
        for i in range(10):
            ch.basic_publish(f"sb{i}".encode(), routing_key="sb_q",
                             properties=PERSISTENT)

        got = []
        done = asyncio.get_event_loop().create_future()

        def on_msg(msg):
            got.append(msg)
            if len(got) == 10 and not done.done():
                done.set_result(None)

        tag = await ch.basic_consume("sb_q", on_msg)
        await asyncio.wait_for(done, 10)
        assert [m.body for m in got] == [f"sb{i}".encode() for i in range(10)]
        # ack the first half, then cancel in the SAME breath: the cancel's
        # control RPC must fence behind the buffered settle batch
        for msg in got[:5]:
            ch.basic_ack(msg.delivery_tag)
        await ch.basic_cancel(tag)
        await asyncio.sleep(0.5)

        queue = owner.server.broker.vhosts["/"].queues["sb_q"]
        assert len(queue.outstanding) == 0
        bodies = sorted(bytes(qm.message.body) for qm in queue.messages)
        # exactly the unacked half, once each — no loss, no duplication
        assert bodies == sorted(f"sb{i}".encode() for i in range(5, 10))
        assert all(qm.redelivered for qm in queue.messages)
        await client.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_interconnect_counters_and_admin_stats(tmp_path):
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "ic_q")
        client = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await client.channel()
        await ch.queue_declare("ic_q")
        for i in range(50):
            ch.basic_publish(f"ic{i}".encode(), routing_key="ic_q")
        await asyncio.sleep(0.5)
        m_other = other.server.broker.metrics
        m_owner = owner.server.broker.metrics
        assert m_other.rpc_push_records == 50
        # micro-batching: far fewer batches than records
        assert 0 < m_other.rpc_push_batches < 50
        assert m_other.rpc_data_bytes_sent > 0
        assert m_owner.rpc_data_bytes_recv > 0
        plane = other.cluster.dataplane(owner.name)
        stats = plane.stats()
        assert stats["streams"] >= 1
        assert stats["buffered_push_records"] == 0  # all flushed
        await client.close()
    finally:
        for node in nodes:
            await node.stop()
