"""Regression tests for codec defects found in review/verification."""

import decimal

import pytest

from chanamq_tpu.amqp import value_codec as vc
from chanamq_tpu.amqp import methods as m
from chanamq_tpu.amqp.command import AMQCommand
from chanamq_tpu.amqp.frame import FrameError, FrameParser
from chanamq_tpu.amqp.properties import BasicProperties


def test_decimal_positive_exponent_roundtrip():
    # 1E+2 must survive as 100, not be scaled down to 1
    out = vc.decode_table(vc.encode_table({"d": decimal.Decimal("1E+2")}))
    assert out["d"] == 100


def test_non_utf8_longstr_reencodes_verbatim():
    raw = b"\x00\x00\x00\x09\x01kS\x00\x00\x00\x02\xff\xfe"
    assert vc.encode_table(vc.decode_table(raw)) == raw


def test_methods_with_tables_are_hashable():
    assert isinstance(hash(m.Queue.Declare(arguments={"x": 1})), int)
    assert hash(m.Basic.Ack(delivery_tag=1)) != hash(m.Basic.Ack(delivery_tag=2))


def test_render_rejects_degenerate_frame_max():
    cmd = AMQCommand(1, m.Basic.Publish(exchange="e"), BasicProperties(), b"abc")
    for bad in (1, 7, 8):
        with pytest.raises(ValueError):
            cmd.render_frames(bad)


def test_parser_rejects_garbage_from_header_alone():
    # corrupt stream with a huge bogus size field must error immediately,
    # not buffer gigabytes waiting for it
    out = list(FrameParser().feed(b"\x41" * 12))
    assert isinstance(out[0], FrameError)


def test_parser_assembler_fuzz_no_crashes():
    """Seeded fuzz over both parsers + the assembler: random garbage,
    bit-flipped valid publishes, and truncations, fed in random chunkings.
    Every input must end in frames, silence, or FrameError — never an
    exception or a hang (the broker's read loop treats anything else as a
    crash)."""
    import random
    import struct

    from chanamq_tpu.amqp.command import CommandAssembler
    from chanamq_tpu.amqp.frame import FrameError, FrameParser
    from chanamq_tpu import native_ext

    rng = random.Random(0xC0DEC)

    def valid_publish(ch):
        m = b"\x00\x3c\x00\x28\x00\x00\x00\x05qq\x00"
        h = struct.pack(">HHQH", 60, 0, 4, 0x1000) + b"\x01"
        b = b"abcd"
        out = b""
        for t, p in ((1, m), (2, h), (3, b)):
            out += struct.pack(">BHI", t, ch, len(p)) + p + b"\xce"
        return out

    parser_classes = [FrameParser]
    if native_ext.available():
        parser_classes.append(native_ext.NativeFrameParser)
    for trial in range(600):
        kind = rng.randrange(3)
        if kind == 0:
            data = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(1, 400)))
        elif kind == 1:
            base = bytearray(
                valid_publish(rng.randrange(1, 4)) * rng.randrange(1, 4))
            for _ in range(rng.randrange(1, 6)):
                base[rng.randrange(len(base))] = rng.randrange(256)
            data = bytes(base)
        else:
            data = valid_publish(1)[:rng.randrange(1, 60)]
        for parser_cls in parser_classes:
            parser = parser_cls()
            parser.frame_max = 131072
            assembler = CommandAssembler()
            pos = 0
            dead = False
            while pos < len(data) and not dead:
                chunk = data[pos:pos + rng.randrange(1, 64)]
                pos += len(chunk)
                for item in parser.feed(chunk):
                    if isinstance(item, FrameError):
                        dead = True
                        break
                    if item.type in (1, 2, 3):
                        out = assembler.feed_one(item)
                        if isinstance(out, FrameError):
                            dead = True
                            break
