"""Liveness / readiness evaluation with reasons.

Liveness is trivially true whenever the process can serve the request
(the event loop is running). Readiness is the load-balancer signal: a
node that is draining, whose event loop is lagging, whose store is
failing background writes, whose replication is far behind, or that has
lost cluster quorum should stop receiving new work — each check
contributes a human-readable reason so /admin/health explains *why*.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker
    from .service import TelemetryService


def shard_check(broker: "Broker") -> "tuple[dict, list[str]] | None":
    """Shard-sibling liveness, usable with or without telemetry: a worker
    in a multi-process node is only ready while every sibling shard
    heartbeats (a dead sibling means part of the queue space is mid-
    re-hash; the LB should drain this node). None when not sharded."""
    shard_info = getattr(broker, "shard_info", None)
    cluster = broker.cluster
    if (shard_info is None or cluster is None
            or cluster.membership is None):
        return None
    siblings = set(cluster.uds_map)
    alive_set = set(cluster.membership.alive_members())
    dead = sorted(siblings - alive_set)
    check = {"ok": not dead, "self": shard_info["index"],
             "count": shard_info["count"], "dead_siblings": dead}
    reasons = ([f"shard sibling(s) down: {', '.join(dead)}"]
               if dead else [])
    return check, reasons


def flow_check(broker: "Broker") -> "tuple[dict, list[str]] | None":
    """Memory-pressure ladder state, usable with or without telemetry
    (the /admin/health fallback needs it too — a default-config broker at
    the refuse stage must not read as ready). The stage is always
    surfaced (so the LB / operator sees "throttle" building), but
    readiness only drops at the refuse stage — a throttling broker is
    still doing useful work, and flipping it not-ready would redirect
    load it is actively shedding. None when no watermark is configured."""
    flow = broker.flow
    if flow is None:
        return None
    from ..flow import STAGE_REFUSE

    refusing = flow.stage >= STAGE_REFUSE
    check = {
        "ok": not refusing, "stage": flow.stage,
        "stage_label": flow.label, "accounted_bytes": flow.total,
        "hard_limit": flow.hard_limit}
    reasons = ([f"memory pressure: stage {flow.label} "
                f"({flow.total} accounted / hard limit {flow.hard_limit})"]
               if refusing else [])
    return check, reasons


def evaluate_health(broker: "Broker", svc: "TelemetryService") -> dict:
    reasons: list[str] = []
    checks: dict[str, dict] = {}

    draining = bool(getattr(broker, "draining", False))
    checks["draining"] = {"ok": not draining}
    if draining:
        reasons.append("draining: shutdown in progress")

    lag_ms = svc.loop_lag_ms
    lag_ok = lag_ms <= svc.loop_lag_ready_ms
    checks["loop_lag"] = {
        "ok": lag_ok, "lag_ms": round(lag_ms, 3),
        "threshold_ms": svc.loop_lag_ready_ms}
    if not lag_ok:
        reasons.append(
            f"event-loop lag {lag_ms:.0f}ms > {svc.loop_lag_ready_ms:.0f}ms")

    # store errors: not-ready while background writes failed in the recent
    # sampling window (a single ancient failure must not wedge readiness
    # forever, so the service tracks a windowed delta, not the total)
    recent = svc.store_errors_recent
    total = int(getattr(broker.store, "error_count", 0))
    checks["store"] = {"ok": recent == 0, "recent_errors": recent,
                       "total_errors": total}
    if recent:
        reasons.append(f"store: {recent} background write failure(s) "
                       f"in the last {svc.store_error_window} ticks")

    pressure = flow_check(broker)
    if pressure is not None:
        checks["memory_pressure"], flow_reasons = pressure
        reasons.extend(flow_reasons)

    cluster = broker.cluster
    repl_lag = 0
    if cluster is not None and cluster.replication is not None:
        repl_lag = int(cluster.replication.total_lag())
    repl_ok = repl_lag <= svc.repl_lag_ready
    checks["replication"] = {
        "ok": repl_ok, "lag_events": repl_lag,
        "threshold_events": svc.repl_lag_ready}
    if not repl_ok:
        reasons.append(
            f"replication lag {repl_lag} events > {svc.repl_lag_ready}")

    if cluster is not None and cluster.membership is not None:
        alive = cluster.membership.alive_members()
        total_n = len(cluster.membership.members)
        # strict majority; a single-node "cluster" is always quorate
        quorate = total_n <= 1 or 2 * len(alive) > total_n
        checks["quorum"] = {
            "ok": quorate, "alive": len(alive), "members": total_n}
        if not quorate:
            reasons.append(
                f"cluster quorum lost ({len(alive)}/{total_n} alive)")

    shards = shard_check(broker)
    if shards is not None:
        checks["shards"], shard_reasons = shards
        reasons.extend(shard_reasons)

    payload = {
        "node": broker.trace_node,
        "live": True,
        "ready": not reasons,
        "reasons": reasons,
        "checks": checks,
    }
    # SLO stamp (informational — burning budgets mean the objective is at
    # risk, not that the node should stop taking traffic, so no reason is
    # added): which SLOs are burning and how much budget remains
    slo = getattr(svc, "slo", None)
    if slo is not None:
        payload["slo"] = slo.readiness_stamp()
    return payload
