"""Host-to-host RPC over TCP.

The DCN control-plane analogue of the reference's Akka artery remoting
(chana-mq-base reference.conf:16-23; messaging pattern SURVEY.md §5:
request/response `ask` with timeout + fire-and-forget `tell`). Wire format
reuses the framework's own AMQP field-table codec for payloads (tables carry
nested tables, byte arrays, ints — everything entity ops need), so the
cluster layer introduces no second serialization scheme and no pickle.

Frame: u32 body-length | u64 correlation-id | u8 kind | shortstr method |
       table payload
kinds: 0=request 1=response 2=error 3=event (fire-and-forget)
"""

from __future__ import annotations

import asyncio
import logging
import struct
from io import BytesIO
from typing import Awaitable, Callable, Optional

from ..amqp import value_codec as vc

log = logging.getLogger("chanamq.rpc")

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ERROR = 2
KIND_EVENT = 3

_HEAD = struct.Struct(">IQB")
MAX_FRAME = 64 * 1024 * 1024

Handler = Callable[[dict], Awaitable[Optional[dict]]]


class RpcError(Exception):
    def __init__(self, code: str, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


class RpcTimeout(RpcError):
    def __init__(self, method: str) -> None:
        super().__init__("timeout", f"rpc {method} timed out")


def _encode(corr_id: int, kind: int, method: str, payload: dict) -> bytes:
    body = BytesIO()
    vc.write_shortstr(body, method)
    vc.write_table(body, payload)
    data = body.getvalue()
    return _HEAD.pack(len(data) + 9, corr_id, kind) + data


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, int, str, dict]:
    head = await reader.readexactly(4)
    (length,) = struct.unpack(">I", head)
    if length > MAX_FRAME:
        raise RpcError("frame_too_large", f"{length} bytes")
    body = await reader.readexactly(length)
    corr_id, kind = struct.unpack_from(">QB", body)
    stream = BytesIO(body[9:])
    method = vc.read_shortstr(stream)
    payload = vc.read_table(stream)
    return corr_id, kind, method, payload


class RpcServer:
    """Listens for peer connections; dispatches requests to handlers."""

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self.handlers: dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._peer_writers: set[asyncio.StreamWriter] = set()

    def register(self, method: str, handler: Handler) -> None:
        self.handlers[method] = handler

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # close accepted connections first: py3.12 wait_closed() blocks
            # until every connection handler finishes
            for writer in list(self._peer_writers):
                try:
                    writer.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._peer_writers.add(writer)
        try:
            while True:
                corr_id, kind, method, payload = await _read_frame(reader)
                if kind == KIND_EVENT:
                    handler = self.handlers.get(method)
                    if handler is not None:
                        # events are fire-and-forget; run concurrently
                        asyncio.get_event_loop().create_task(
                            self._run_event(handler, method, payload))
                    continue
                if kind != KIND_REQUEST:
                    continue
                asyncio.get_event_loop().create_task(
                    self._run_request(writer, corr_id, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("rpc server connection failed")
        finally:
            self._peer_writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _run_event(self, handler: Handler, method: str, payload: dict) -> None:
        try:
            await handler(payload)
        except Exception:
            log.exception("rpc event handler %s failed", method)

    async def _run_request(
        self, writer: asyncio.StreamWriter, corr_id: int, method: str, payload: dict
    ) -> None:
        handler = self.handlers.get(method)
        try:
            if handler is None:
                raise RpcError("no_such_method", method)
            result = await handler(payload)
            frame = _encode(corr_id, KIND_RESPONSE, method, result or {})
        except RpcError as exc:
            frame = _encode(corr_id, KIND_ERROR, method,
                            {"code": exc.code, "message": exc.message})
        except Exception as exc:
            log.exception("rpc handler %s failed", method)
            frame = _encode(corr_id, KIND_ERROR, method,
                            {"code": "internal", "message": str(exc)})
        try:
            writer.write(frame)
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass


class RpcClient:
    """One outgoing connection to a peer, with correlation-id matching.
    Reconnects lazily on next call after a drop."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 20.0) -> None:
        self.host = host
        self.port = port
        self.timeout_s = timeout_s  # the reference's 20 s internal ask timeout
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._waiters: dict[int, asyncio.Future] = {}
        self._next_corr = 1
        self._connect_lock = asyncio.Lock()
        self.closed = False

    async def _ensure_connected(self) -> asyncio.StreamWriter:
        if self._writer is not None and not self._writer.is_closing():
            return self._writer
        async with self._connect_lock:
            if self._writer is not None and not self._writer.is_closing():
                return self._writer
            reader, writer = await asyncio.open_connection(self.host, self.port)
            self._writer = writer
            self._reader_task = asyncio.get_event_loop().create_task(
                self._read_loop(reader, writer))
            return writer

    async def _read_loop(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                corr_id, kind, _method, payload = await _read_frame(reader)
                fut = self._waiters.pop(corr_id, None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESPONSE:
                    fut.set_result(payload)
                elif kind == KIND_ERROR:
                    fut.set_exception(RpcError(
                        str(payload.get("code", "unknown")),
                        str(payload.get("message", ""))))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._fail_waiters(RpcError("disconnected", f"{self.host}:{self.port}"))
            # close OUR writer (dead peer), not whatever reconnect may have
            # installed since; abandoning it would leak the socket until GC
            if self._writer is writer:
                self._writer = None
            try:
                writer.close()
            except Exception:
                pass

    def _fail_waiters(self, exc: Exception) -> None:
        for fut in self._waiters.values():
            if not fut.done():
                fut.set_exception(exc)
        self._waiters.clear()

    async def call(
        self, method: str, payload: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> dict:
        writer = await self._ensure_connected()
        corr_id = self._next_corr
        self._next_corr += 1
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters[corr_id] = fut
        writer.write(_encode(corr_id, KIND_REQUEST, method, payload or {}))
        await writer.drain()
        try:
            return await asyncio.wait_for(fut, timeout_s or self.timeout_s)
        except asyncio.TimeoutError:
            self._waiters.pop(corr_id, None)
            raise RpcTimeout(method) from None

    async def send_event(self, method: str, payload: Optional[dict] = None) -> None:
        """Fire-and-forget (the reference's `tell`)."""
        writer = await self._ensure_connected()
        writer.write(_encode(0, KIND_EVENT, method, payload or {}))
        await writer.drain()

    async def close(self) -> None:
        self.closed = True
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
            self._writer = None
        self._fail_waiters(RpcError("closed", "client closed"))
