"""Predictive control plane (ROADMAP item 4): closes the loop from the
JAX forecaster's next-tick predictions to the broker's existing
actuators — the 4-stage flow ladder, per-connection publish credit,
cluster holdership, and the consume-credit window.

``engine``  — pure, deterministic decision evaluation (no I/O, no clocks)
``service`` — sampling + actuation on the event loop, evaluation off it
"""
from .engine import ControlConfig, ControlEngine, ControlInputs, QueueInput
from .service import ControlService

__all__ = [
    "ControlConfig",
    "ControlEngine",
    "ControlInputs",
    "QueueInput",
    "ControlService",
]
