"""W3C trace-context primitives (https://www.w3.org/TR/trace-context/).

Pure functions + one tiny value class, importable from the trace runtime
without cycles (this module imports nothing from the broker). Two rules
shape everything here:

- a malformed ``traceparent`` must never break the publish carrying it
  (the W3C spec says: restart the trace), so every parser returns None
  instead of raising;
- a forced sample must not perturb the seeded sampling sequence, so
  every id the broker mints for a propagated trace is *derived* (SHA-256
  of stable inputs), never drawn from an RNG.
"""

from __future__ import annotations

import hashlib
from typing import Optional

TRACEPARENT_HEADER = "traceparent"
TRACESTATE_HEADER = "tracestate"

_HEX = frozenset("0123456789abcdef")


def _is_hex(text: str) -> bool:
    return bool(text) and all(c in _HEX for c in text)


class W3CContext:
    """The propagated context pinned on one broker-side trace.

    ``trace_id``/``parent_span_id`` come off the client's traceparent;
    ``root_span_id`` is the broker's own span for this hop — every stage
    span parents to it, and it is what rides outgoing headers so the
    next hop (consumer, or a federated mirror) parents to this broker.
    """

    __slots__ = ("trace_id", "parent_span_id", "root_span_id", "flags",
                 "tracestate")

    def __init__(self, trace_id: str, parent_span_id: str,
                 root_span_id: str, flags: int = 1,
                 tracestate: Optional[str] = None) -> None:
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.root_span_id = root_span_id
        self.flags = flags
        self.tracestate = tracestate

    @property
    def outgoing(self) -> str:
        """The traceparent this broker stamps on everything it emits.

        Always sampled (01): a context only reaches here by forcing a
        sample, and downstream hops must keep the trace joined."""
        return f"00-{self.trace_id}-{self.root_span_id}-01"

    def to_dict(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "parent_span_id": self.parent_span_id,
            "root_span_id": self.root_span_id,
            "flags": self.flags,
        }
        if self.tracestate:
            out["tracestate"] = self.tracestate
        return out


def parse_traceparent(value) -> "Optional[tuple[str, str, int]]":
    """``(trace_id, parent_span_id, flags)``, or None if malformed.

    Accepts str or bytes (AMQP tables carry either). Rejection cases per
    the spec: version ``ff``, short/overlong or non-hex ids, the all-zero
    trace or parent id, and a version-00 header with trailing fields
    (future versions may append fields, 00 may not)."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        try:
            value = bytes(value).decode("ascii")
        except UnicodeDecodeError:
            return None
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    ver, tid, pid, flags = parts[0], parts[1], parts[2], parts[3]
    if len(ver) != 2 or not _is_hex(ver) or ver == "ff":
        return None
    if ver == "00" and len(parts) != 4:
        return None
    if len(tid) != 32 or not _is_hex(tid) or tid == "0" * 32:
        return None
    if len(pid) != 16 or not _is_hex(pid) or pid == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return tid, pid, int(flags, 16)


def extract(headers) -> "Optional[tuple[str, str, int, Optional[str]]]":
    """Lift ``(trace_id, parent_span_id, flags, tracestate)`` off an AMQP
    header table; None when absent or malformed (the publish proceeds on
    the normal seeded-sampling path either way)."""
    if not headers:
        return None
    raw = headers.get(TRACEPARENT_HEADER)
    if raw is None:
        return None
    parsed = parse_traceparent(raw)
    if parsed is None:
        return None
    state = headers.get(TRACESTATE_HEADER)
    if isinstance(state, (bytes, bytearray, memoryview)):
        try:
            state = bytes(state).decode("ascii")
        except UnicodeDecodeError:
            state = None
    if not isinstance(state, str) or not state:
        state = None
    return parsed[0], parsed[1], parsed[2], state


def format_traceparent(trace_id: str, span_id: str, flags: int = 1) -> str:
    return f"00-{trace_id}-{span_id}-{flags & 0xFF:02x}"


def derive_span_id(*parts: str) -> str:
    """Deterministic 8-byte span id (16 hex chars) from stable inputs.

    Derivation instead of randomness keeps two invariants: forced
    samples never consume the seeded sampling RNG, and re-rendering the
    same trace (push export then pull fallback) yields identical ids."""
    digest = hashlib.sha256(":".join(parts).encode()).digest()[:8]
    if digest == b"\x00" * 8:  # the all-zero span id is invalid
        digest = b"\x01" + digest[1:]
    return digest.hex()


def derive_trace_id(internal_id: str) -> str:
    """32-hex OTLP trace id for a seeded (headerless) sample, derived
    from the internal ``node#seq`` id so exports are stable per trace."""
    digest = hashlib.sha256(internal_id.encode()).digest()[:16]
    if digest == b"\x00" * 16:
        digest = b"\x01" + digest[1:]
    return digest.hex()


def stamp_headers(properties, ctx: W3CContext):
    """Copy-on-write rewrite of a BasicProperties with the outgoing
    context. Returns ``(properties, changed)``; when changed, callers
    must drop any cached header_raw so the next render re-encodes.

    COPY, never mutate: the connection layer's header cache shares
    BasicProperties objects across publishes with identical header
    bytes, so an in-place header write would poison unrelated messages.
    The rewrite is idempotent (same outgoing value -> unchanged), which
    keeps the remote-apply re-stamp on clustered pushes a no-op."""
    outgoing = ctx.outgoing
    headers = properties.headers
    if headers is not None and headers.get(TRACEPARENT_HEADER) == outgoing:
        return properties, False
    new_headers = dict(headers or {})
    new_headers[TRACEPARENT_HEADER] = outgoing
    if ctx.tracestate:
        new_headers[TRACESTATE_HEADER] = ctx.tracestate
    props = properties.copy()
    props.headers = new_headers
    return props, True
