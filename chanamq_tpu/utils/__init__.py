"""Shared utilities: metrics, logging, config."""
