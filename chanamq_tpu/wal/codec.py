"""WAL record codec: framed, CRC-checked, length-prefixed op records.

Every durable store mutation becomes one record in the shard's log:

    u32 payload_len | u32 crc32(payload) | payload
    payload = u64 lsn | u8 op_index | encoded args tuple

The op index into :data:`OPS` is wire format (append-only, like the
trace stage tags); the argument values are encoded with a compact
self-describing binary codec covering exactly the types the store API
carries — None/bool/int/float/bytes/str/list/tuple/dict plus the three
Stored* dataclasses. No pickle: replay of a hostile or corrupted log
must never execute anything, only reconstruct data.

Tail semantics on read-back (scan_frames): a frame that runs past the
end of the file, or whose CRC fails on the very last frame, is a *torn*
write — the crash interrupted the append and everything before it is
intact, so recovery truncates the tail and replays the rest.  A CRC
failure with more data behind it is *corruption* — ordering below the
bad record can't be trusted, so replay stops there (skip-and-stop).
"""

from __future__ import annotations

import struct
from zlib import crc32

from ..store.api import StoredExchange, StoredMessage, StoredQueue

# Journaled op names. Index is wire format: append-only, never reorder.
OPS = (
    "insert_message",
    "delete_message",
    "delete_messages",
    "update_message_refer_count",
    "insert_queue_meta",
    "insert_queue_msg",
    "delete_queue_msg",
    "replace_queue_msgs",
    "replace_queue_unacks",
    "update_queue_last_consumed",
    "insert_queue_unacks",
    "delete_queue_msgs_offsets",
    "delete_queue_unacks",
    "archive_queue",
    "delete_queue",
    "purge_queue_msgs",
    "insert_stream_segment",
    "delete_stream_segments",
    "update_stream_cursor",
    "delete_stream_data",
    "insert_exchange",
    "delete_exchange",
    "insert_bind",
    "delete_bind",
    "delete_queue_binds",
    "insert_exchange_bind",
    "delete_exchange_bind",
    "delete_exchange_binds_dest",
    "insert_vhost",
    "delete_vhost",
    "worker_id_floor",  # replay-only: next_worker_id = max(current, n)
    # fused persistent publish: (msg, vhost, queue, offset, body_size,
    # expire_at_ms) — one record covers the blob and its queue-log row, so
    # the hot path frames (and CRCs) once per publish instead of twice.
    # Appended after the fact: wire indices above never move.
    "insert_published",
    # atomic transaction scope: ([(op_index, args), ...],) — every store
    # mutation a Tx.Commit staged, framed as ONE record with ONE CRC.
    # scan_frames cannot split inside a frame, so a crash either keeps the
    # whole transaction (record durable) or loses it whole (torn tail
    # truncated): the all-or-nothing guarantee multi-record commits cannot
    # give, because a group-commit batch can tear at record granularity.
    "tx_batch",
)
OP_INDEX = {name: i for i, name in enumerate(OPS)}

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")

# frames larger than this are treated as corruption on read-back (the
# engine never writes one: segment-bytes caps far below it)
MAX_FRAME = 256 * 1024 * 1024


class WalCodecError(ValueError):
    pass


# -- value codec -------------------------------------------------------------

def _enc_value(buf: bytearray, v) -> None:
    if v is None:
        buf += b"N"
    elif v is True:
        buf += b"T"
    elif v is False:
        buf += b"F"
    elif type(v) is int:
        if -(1 << 63) <= v < (1 << 63):
            buf += b"i"
            buf += _I64.pack(v)
        else:  # arbitrary-precision fallback (arguments dicts)
            raw = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
            buf += b"I"
            buf += _U32.pack(len(raw))
            buf += raw
    elif type(v) is float:
        buf += b"f"
        buf += _F64.pack(v)
    elif type(v) is bytes or type(v) is bytearray or type(v) is memoryview:
        raw = bytes(v)
        buf += b"b"
        buf += _U32.pack(len(raw))
        buf += raw
    elif type(v) is str:
        raw = v.encode("utf-8")
        buf += b"s"
        buf += _U32.pack(len(raw))
        buf += raw
    elif type(v) is list:
        buf += b"l"
        buf += _U32.pack(len(v))
        for item in v:
            _enc_value(buf, item)
    elif type(v) is tuple:
        buf += b"t"
        buf += _U32.pack(len(v))
        for item in v:
            _enc_value(buf, item)
    elif type(v) is dict:
        buf += b"d"
        buf += _U32.pack(len(v))
        for k, item in v.items():
            _enc_value(buf, k)
            _enc_value(buf, item)
    elif type(v) is StoredMessage:
        buf += b"M"
        _enc_value(buf, (v.id, v.properties_raw, v.body, v.exchange,
                         v.routing_key, v.refer_count, v.ttl_ms))
    elif type(v) is StoredQueue:
        buf += b"Q"
        _enc_value(buf, (v.vhost, v.name, v.durable, v.exclusive,
                         v.auto_delete, v.ttl_ms, v.last_consumed,
                         v.arguments, v.msgs, v.unacks))
    elif type(v) is StoredExchange:
        buf += b"X"
        _enc_value(buf, (v.vhost, v.name, v.type, v.durable, v.auto_delete,
                         v.internal, v.arguments, v.binds, v.ex_binds))
    else:
        raise WalCodecError(f"unencodable value type {type(v).__name__}")


def _dec_value(view, pos: int):
    tag = view[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"i":
        return _I64.unpack_from(view, pos)[0], pos + 8
    if tag == b"I":
        n = _U32.unpack_from(view, pos)[0]
        pos += 4
        return int.from_bytes(bytes(view[pos:pos + n]), "little",
                              signed=True), pos + n
    if tag == b"f":
        return _F64.unpack_from(view, pos)[0], pos + 8
    if tag == b"b":
        n = _U32.unpack_from(view, pos)[0]
        pos += 4
        return bytes(view[pos:pos + n]), pos + n
    if tag == b"s":
        n = _U32.unpack_from(view, pos)[0]
        pos += 4
        return bytes(view[pos:pos + n]).decode("utf-8"), pos + n
    if tag in (b"l", b"t"):
        n = _U32.unpack_from(view, pos)[0]
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _dec_value(view, pos)
            items.append(item)
        return (tuple(items) if tag == b"t" else items), pos
    if tag == b"d":
        n = _U32.unpack_from(view, pos)[0]
        pos += 4
        out = {}
        for _ in range(n):
            k, pos = _dec_value(view, pos)
            v, pos = _dec_value(view, pos)
            out[k] = v
        return out, pos
    if tag == b"M":
        f, pos = _dec_value(view, pos)
        return StoredMessage(id=f[0], properties_raw=f[1], body=f[2],
                             exchange=f[3], routing_key=f[4],
                             refer_count=f[5], ttl_ms=f[6]), pos
    if tag == b"Q":
        f, pos = _dec_value(view, pos)
        return StoredQueue(vhost=f[0], name=f[1], durable=f[2],
                           exclusive=f[3], auto_delete=f[4], ttl_ms=f[5],
                           last_consumed=f[6], arguments=f[7],
                           msgs=list(f[8]), unacks=dict(f[9])), pos
    if tag == b"X":
        f, pos = _dec_value(view, pos)
        return StoredExchange(vhost=f[0], name=f[1], type=f[2], durable=f[3],
                              auto_delete=f[4], internal=f[5], arguments=f[6],
                              binds=list(f[7]), ex_binds=list(f[8])), pos
    raise WalCodecError(f"bad value tag {tag!r} at {pos - 1}")


# -- hot-path framing --------------------------------------------------------
# The two ops every persistent publish journals (message blob + queue-log
# row) get hand-rolled builders: same wire bytes as encode_record, but one
# join instead of a recursive _enc_value walk (~3x fewer Python calls on
# the broker's event loop).  Any shape the fast path can't prove — exotic
# types, oversize ints — returns None and the caller falls back to the
# generic encoder, so the format stays defined in exactly one place.

_HDR = struct.Struct("<II")
_OP_INS_MSG = bytes([OP_INDEX["insert_message"]])
_OP_INS_QMSG = bytes([OP_INDEX["insert_queue_msg"]])
_OP_INS_PUB = bytes([OP_INDEX["insert_published"]])
_I64_MAX = 1 << 63


def encode_insert_message(lsn: int, msg) -> "bytes | None":
    body = msg.body
    props = msg.properties_raw
    ttl = msg.ttl_ms
    if (type(body) is not bytes or type(props) is not bytes
            or type(msg.exchange) is not str
            or type(msg.routing_key) is not str
            or not (type(msg.id) is int and 0 <= msg.id < _I64_MAX)
            or not (type(msg.refer_count) is int
                    and -_I64_MAX <= msg.refer_count < _I64_MAX)):
        return None
    if ttl is None:
        tail = b"N"
    elif type(ttl) is int and -_I64_MAX <= ttl < _I64_MAX:
        tail = b"i" + _I64.pack(ttl)
    else:
        return None
    exb = msg.exchange.encode("utf-8")
    rkb = msg.routing_key.encode("utf-8")
    payload = b"".join((
        _U64.pack(lsn), _OP_INS_MSG,
        b"t\x01\x00\x00\x00M" b"t\x07\x00\x00\x00",
        b"i", _I64.pack(msg.id),
        b"b", _U32.pack(len(props)), props,
        b"b", _U32.pack(len(body)), body,
        b"s", _U32.pack(len(exb)), exb,
        b"s", _U32.pack(len(rkb)), rkb,
        b"i", _I64.pack(msg.refer_count),
        tail,
    ))
    return _HDR.pack(len(payload), crc32(payload)) + payload


def queue_prefix(vhost: str, queue: str) -> bytes:
    """Encoded (vhost, queue) string pair — the per-queue constant chunk of
    row payloads; callers cache it so the hot path packs only the ints."""
    vb = vhost.encode("utf-8")
    qb = queue.encode("utf-8")
    return (b"s" + _U32.pack(len(vb)) + vb
            + b"s" + _U32.pack(len(qb)) + qb)


def encode_insert_queue_msg(lsn: int, vq: bytes, offset: int,
                            msg_id: int, body_size: int,
                            expire_at_ms) -> "bytes | None":
    if expire_at_ms is None:
        tail = b"N"
    elif type(expire_at_ms) is int and -_I64_MAX <= expire_at_ms < _I64_MAX:
        tail = b"i" + _I64.pack(expire_at_ms)
    else:
        return None
    if not (type(offset) is int and 0 <= offset < _I64_MAX
            and type(msg_id) is int and 0 <= msg_id < _I64_MAX
            and type(body_size) is int and 0 <= body_size < _I64_MAX):
        return None
    payload = b"".join((
        _U64.pack(lsn), _OP_INS_QMSG,
        b"t\x06\x00\x00\x00", vq,
        b"i", _I64.pack(offset),
        b"i", _I64.pack(msg_id),
        b"i", _I64.pack(body_size),
        tail,
    ))
    return _HDR.pack(len(payload), crc32(payload)) + payload


def encode_insert_published(lsn: int, msg, vq: bytes, offset: int,
                            body_size: int, expire_at_ms) -> "bytes | None":
    body = msg.body
    props = msg.properties_raw
    ttl = msg.ttl_ms
    if (type(body) is not bytes or type(props) is not bytes
            or type(msg.exchange) is not str
            or type(msg.routing_key) is not str
            or not (type(msg.id) is int and 0 <= msg.id < _I64_MAX)
            or not (type(msg.refer_count) is int
                    and -_I64_MAX <= msg.refer_count < _I64_MAX)
            or not (type(offset) is int and 0 <= offset < _I64_MAX)
            or not (type(body_size) is int and 0 <= body_size < _I64_MAX)):
        return None
    if ttl is None:
        ttl_tail = b"N"
    elif type(ttl) is int and -_I64_MAX <= ttl < _I64_MAX:
        ttl_tail = b"i" + _I64.pack(ttl)
    else:
        return None
    if expire_at_ms is None:
        exp_tail = b"N"
    elif (type(expire_at_ms) is int
            and -_I64_MAX <= expire_at_ms < _I64_MAX):
        exp_tail = b"i" + _I64.pack(expire_at_ms)
    else:
        return None
    exb = msg.exchange.encode("utf-8")
    rkb = msg.routing_key.encode("utf-8")
    payload = b"".join((
        _U64.pack(lsn), _OP_INS_PUB,
        b"t\x06\x00\x00\x00" b"M" b"t\x07\x00\x00\x00",
        b"i", _I64.pack(msg.id),
        b"b", _U32.pack(len(props)), props,
        b"b", _U32.pack(len(body)), body,
        b"s", _U32.pack(len(exb)), exb,
        b"s", _U32.pack(len(rkb)), rkb,
        b"i", _I64.pack(msg.refer_count),
        ttl_tail,
        vq,
        b"i", _I64.pack(offset),
        b"i", _I64.pack(body_size),
        exp_tail,
    ))
    return _HDR.pack(len(payload), crc32(payload)) + payload


# -- record framing ----------------------------------------------------------

def encode_record(lsn: int, op_index: int, args: tuple) -> bytes:
    payload = bytearray()
    payload += _U64.pack(lsn)
    payload.append(op_index)
    _enc_value(payload, args)
    payload = bytes(payload)
    return _U32.pack(len(payload)) + _U32.pack(crc32(payload)) + payload


def decode_payload(payload) -> "tuple[int, int, tuple]":
    view = memoryview(payload)
    lsn = _U64.unpack_from(view, 0)[0]
    op = view[8]
    args, end = _dec_value(view, 9)
    if end != len(view) or type(args) is not tuple:
        raise WalCodecError("record payload has trailing garbage")
    return lsn, op, args


def scan_frames(data) -> "tuple[list[bytes], int, str]":
    """Walk a segment's bytes frame by frame.

    Returns (payloads, good_bytes, status) where status is:
      "ok"      — every byte consumed by valid frames;
      "torn"    — the final frame was cut mid-write (runs past EOF, or
                  its CRC fails and nothing follows): truncate the tail
                  at good_bytes and keep everything before it;
      "corrupt" — a CRC failure with more data behind it: stop here, the
                  rest of the log cannot be trusted.
    """
    view = memoryview(data)
    total = len(view)
    pos = 0
    payloads: list[bytes] = []
    while pos < total:
        if total - pos < 8:
            return payloads, pos, "torn"
        length = _U32.unpack_from(view, pos)[0]
        want = _U32.unpack_from(view, pos + 4)[0]
        end = pos + 8 + length
        if length == 0 or length > MAX_FRAME:
            return payloads, pos, "torn" if end >= total else "corrupt"
        if end > total:
            return payloads, pos, "torn"
        payload = bytes(view[pos + 8:end])
        if crc32(payload) != want:
            return payloads, pos, "torn" if end == total else "corrupt"
        payloads.append(payload)
        pos = end
    return payloads, pos, "ok"
