"""Cluster-wide unique message ids (snowflake scheme).

Capability parity with the reference's IdGenerator
(chana-mq-server .../service/IdGenerator.scala:13-92): 64-bit ids composed of
a 42-bit millisecond timestamp (custom epoch) << 22 | 10-bit worker id |
12-bit per-ms sequence; monotonic, spin-to-next-ms on sequence overflow,
clock-regression rejected.
"""

from __future__ import annotations

import threading
import time

# custom epoch: 2020-01-01T00:00:00Z, giving 42 bits of headroom for ~139 years
EPOCH_MS = 1577836800000

WORKER_BITS = 10
SEQUENCE_BITS = 12
MAX_WORKER_ID = (1 << WORKER_BITS) - 1
SEQUENCE_MASK = (1 << SEQUENCE_BITS) - 1
TIMESTAMP_SHIFT = WORKER_BITS + SEQUENCE_BITS


class ClockRegressionError(RuntimeError):
    pass


class IdGenerator:
    """Thread-safe snowflake id generator for one worker (node)."""

    __slots__ = ("worker_id", "_lock", "_last_ms", "_sequence")

    def __init__(self, worker_id: int) -> None:
        if not 0 <= worker_id <= MAX_WORKER_ID:
            raise ValueError(f"worker_id must be in [0, {MAX_WORKER_ID}]")
        self.worker_id = worker_id
        self._lock = threading.Lock()
        self._last_ms = -1
        self._sequence = 0

    def next_id(self) -> int:
        # runs once per published message; the uncontended lock stays for
        # thread-safety but the id math lives inline, no extra call frame
        with self._lock:
            now = int(time.time() * 1000)
            if now < self._last_ms:
                raise ClockRegressionError(
                    f"clock moved backwards: {self._last_ms - now} ms"
                )
            if now == self._last_ms:
                self._sequence = (self._sequence + 1) & SEQUENCE_MASK
                if self._sequence == 0:
                    while now <= self._last_ms:
                        now = int(time.time() * 1000)
            else:
                self._sequence = 0
            self._last_ms = now
            return (
                ((now - EPOCH_MS) << TIMESTAMP_SHIFT)
                | (self.worker_id << SEQUENCE_BITS)
                | self._sequence
            )

    def next_ids(self, n: int) -> list[int]:
        # cold path (worker-lease batches): re-acquiring the uncontended
        # lock per id keeps exactly one copy of the snowflake algorithm
        return [self.next_id() for _ in range(n)]

    @staticmethod
    def timestamp_ms(message_id: int) -> int:
        """Extract the creation time (unix ms) from an id."""
        return (message_id >> TIMESTAMP_SHIFT) + EPOCH_MS
