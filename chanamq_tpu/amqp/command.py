"""AMQCommand (method [+ header + body]) rendering and reassembly.

Capability parity with the reference's AMQCommand.render
(chana-mq-base .../model/AMQCommand.scala:29-65) and CommandAssembler state
machine (.../engine/CommandAssembler.scala:44-131): a command is one METHOD
frame, optionally followed by one HEADER frame and zero or more BODY frames;
rendering fragments the body into <= (frame_max - overhead) chunks; assembly
is an incremental state machine fed complete frames.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

from .constants import FRAME_OVERHEAD, ErrorCode, FrameType
from .frame import Frame, FrameError
from .methods import Method, MethodDecodeError, decode_method
from .properties import BasicProperties


@dataclass(slots=True)
class AMQCommand:
    """A fully-assembled AMQP command on one channel."""

    channel: int
    method: Method
    properties: Optional[BasicProperties] = None
    body: bytes = b""
    # Raw HEADER-frame payload as received off the wire (class-id + weight +
    # body-size + property flags/values). Kept so re-rendering the same
    # content (delivery of a just-published message, mandatory returns,
    # persistence) skips the property re-encode — the bytes are identical.
    header_raw: Optional[bytes] = None

    def render_frames(self, frame_max: int) -> list[Frame]:
        if frame_max and frame_max <= FRAME_OVERHEAD:
            raise ValueError(f"frame_max {frame_max} leaves no room for payload")
        frames = [Frame.method(self.channel, self.method.encode())]
        if self.method.HAS_CONTENT:
            header_payload = self.header_raw
            if header_payload is None:
                props = self.properties or BasicProperties()
                header_payload = props.encode_header(len(self.body))
            frames.append(Frame.header(self.channel, header_payload))
            body = self.body
            max_payload = (frame_max - FRAME_OVERHEAD) if frame_max else max(len(body), 1)
            for off in range(0, len(body), max_payload):
                frames.append(Frame.body(self.channel, body[off : off + max_payload]))
        return frames

    def render(self, frame_max: int) -> bytes:
        return b"".join(f.to_bytes() for f in self.render_frames(frame_max))


class CommandAssembler:
    """Reassembles frames into commands for one connection (all channels).

    Feed it complete frames; it yields `AMQCommand` or `FrameError`.
    Heartbeat frames are not handled here — filter them before feeding.

    max_body_size (0 = unlimited) bounds the declared content size: body
    chunks accumulate here until the declared size arrives, so without a
    cap a peer declaring a huge body could grow broker RAM without limit
    (the reference's FrameParser carried the same guard as its
    message-size limit, FrameParser.scala:67-158). The AGGREGATE declared
    size across all channels is additionally bounded at 4x the per-message
    cap: without it, a connection could park one near-cap partial on every
    channel (channel-max of them) and hold cap x channels of RAM invisible
    to the broker's memory gauge."""

    __slots__ = ("_partial", "max_body_size", "_declared_bytes")

    def __init__(self, max_body_size: int = 0) -> None:
        # channel id -> in-flight (command, expected_body_size, received_size)
        self._partial: dict[int, _Partial] = {}
        self.max_body_size = max_body_size
        # sum of expected_size over in-flight partials (declared-size
        # accounting: chunks can never exceed declared + one frame, so
        # bounding declarations bounds memory at message granularity)
        self._declared_bytes = 0

    def feed_one(self, frame: Frame) -> "AMQCommand | FrameError | None":
        """Feed one frame; returns the completed command, a protocol error,
        or None while content is still pending. The hot-loop shape (plain
        call, no generator per frame): every frame produces at most one
        result by construction."""
        channel = frame.channel
        partial = self._partial.get(channel)
        if frame.type == FrameType.METHOD:
            if partial is not None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"method frame while content pending on channel {channel}",
                )
            try:
                method = decode_method(frame.payload)
            except MethodDecodeError as exc:
                return FrameError(ErrorCode.COMMAND_INVALID, str(exc))
            except Exception as exc:
                return FrameError(ErrorCode.SYNTAX_ERROR, f"bad method arguments: {exc}")
            if method.HAS_CONTENT:
                self._partial[channel] = _Partial(AMQCommand(channel, method))
                return None
            return AMQCommand(channel, method)
        elif frame.type == FrameType.BODY:
            if partial is None or partial.expected_size is None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"unexpected body frame on channel {channel}",
                )
            partial.chunks.append(frame.payload)
            partial.received += len(frame.payload)
            if partial.received > partial.expected_size:
                del self._partial[channel]
                self._declared_bytes -= partial.expected_size
                return FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"body overflows declared size on channel {channel}",
                )
            if partial.received == partial.expected_size:
                partial.command.body = b"".join(partial.chunks)
                del self._partial[channel]
                self._declared_bytes -= partial.expected_size
                return partial.command
            return None
        elif frame.type == FrameType.HEADER:
            if partial is None or partial.expected_size is not None:
                return FrameError(
                    ErrorCode.UNEXPECTED_FRAME,
                    f"unexpected header frame on channel {channel}",
                )
            try:
                _class_id, body_size, props = BasicProperties.decode_header(frame.payload)
            except Exception as exc:
                return FrameError(ErrorCode.SYNTAX_ERROR, f"bad content header: {exc}")
            if self.max_body_size and body_size > self.max_body_size:
                del self._partial[channel]
                return FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"declared body size {body_size} exceeds max message "
                    f"size {self.max_body_size}")
            if self.max_body_size and (self._declared_bytes + body_size
                                       > 4 * self.max_body_size):
                del self._partial[channel]
                return FrameError(
                    ErrorCode.FRAME_ERROR,
                    f"aggregate in-flight content "
                    f"{self._declared_bytes + body_size} exceeds "
                    f"{4 * self.max_body_size}")
            partial.command.properties = props
            partial.command.header_raw = frame.payload
            partial.expected_size = body_size
            if body_size == 0:
                del self._partial[channel]
                return partial.command
            self._declared_bytes += body_size
            return None
        else:
            return FrameError(ErrorCode.UNEXPECTED_FRAME, f"frame type {frame.type}")

    def feed(self, frame: Frame) -> Iterator["AMQCommand | FrameError"]:
        result = self.feed_one(frame)
        if result is not None:
            yield result

    def abort_channel(self, channel: int) -> None:
        """Drop any in-flight content on a channel (e.g. on channel close)."""
        partial = self._partial.pop(channel, None)
        if partial is not None and partial.expected_size:
            self._declared_bytes -= partial.expected_size


@dataclass(slots=True)
class _Partial:
    command: AMQCommand
    expected_size: Optional[int] = None
    received: int = 0
    chunks: list[bytes] = field(default_factory=list)
