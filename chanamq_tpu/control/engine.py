"""Pure decision engine for the predictive control plane.

The split mirrors ``chanamq_tpu/models``: the engine is a deterministic
function of one input snapshot plus its own hysteresis counters — no
clocks, no broker references, no I/O — so the same telemetry series
always produces the same decision log (asserted byte-for-byte in
tests/test_control.py and by ``bench.py --control``), and any logged
decision can be replayed from the inputs recorded alongside it.

Three decision kinds, evaluated in a fixed order each tick:

``admission.prearm`` / ``admission.relax``
    When projected resident bytes (current gate total plus the horizon's
    net inflow, from the forecaster when it is fresh and trusted, else
    from the observed gate-growth trend) would cross the stage-2
    watermark, pre-arm the flow ladder: pin a stage floor of THROTTLE
    and shrink the per-connection publish credit, so Channel.Flow and
    credit gating engage *before* the cliff instead of at it. Relax
    reverses both once projection and gate total sit inside the stage-2
    exit band.

``rebalance.move``
    When this node's inflow load diverges from the cluster mean by the
    configured ratio, hand the busiest movable queue to the least-loaded
    peer through the existing holdership machinery.

``prefetch.tune``
    Nudge the cluster consume-credit window from deliver-rate vs
    ack-rate: shrink when consumers ack slower than they are fed (the
    window is hiding latency), grow when acks keep pace and backlog is
    (or is forecast to be) building.

Every trigger is hysteresis-guarded: it must hold for ``arm_ticks``
consecutive ticks and respect a per-kind cooldown; triggers blocked by
either are counted as suppressed.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..flow import STAGE_THROTTLE

# evaluation (and decision-id) order is fixed so logs are reproducible
KINDS = ("admission", "rebalance", "prefetch")


@dataclass(frozen=True)
class ControlConfig:
    horizon_ticks: int = 5          # projection lookahead, in control ticks
    arm_ticks: int = 2              # consecutive trigger ticks before acting
    cooldown_ticks: int = 10        # min ticks between admission decisions
    credit_factor: float = 0.5      # publish-credit shrink on pre-arm
    credit_min: int = 4096
    rebalance_ratio: float = 1.5    # self load vs cluster mean divergence
    rebalance_min_rate: float = 1024.0   # bytes/s floor before moving queues
    rebalance_cooldown_ticks: int = 30
    prefetch_min: int = 8
    prefetch_max: int = 256
    prefetch_lag: float = 0.5       # ack_rate below this fraction of
    prefetch_keep: float = 0.9      # deliver_rate shrinks; above this grows
    prefetch_cooldown_ticks: int = 10


@dataclass(frozen=True)
class QueueInput:
    """One queue's realized tick plus its forecast slot (when pinned)."""
    vhost: str
    name: str
    depth: float
    publish_rate: float
    deliver_rate: float
    ack_rate: float
    ready_bytes: float
    consumers: float
    movable: bool = False
    forecast_depth: Optional[float] = None


@dataclass
class ControlInputs:
    """Snapshot of everything one evaluation reads, gathered on the
    event loop; the engine itself runs off-loop against this copy."""
    tick: int
    interval_s: float
    stage: int
    floor: int
    gate_total: int
    enter_throttle: int
    exit_throttle: int
    net_rate: float                 # observed gate-total growth, bytes/s
    publish_credit: int
    forecast_net_rate: Optional[float] = None   # trusted forecast, bytes/s
    queues: tuple = ()
    node: str = "local"
    self_load: float = 0.0          # inflow EWMA, bytes/s
    peer_loads: dict = field(default_factory=dict)
    consume_credit: Optional[int] = None
    # a member that just joined (set by the service for a bounded window):
    # backlog should drain onto it even when this node's load does not
    # diverge from the cluster mean yet
    join_target: Optional[str] = None


def _r(value: float) -> float:
    """Round floats entering the decision log so serialization is stable."""
    return round(float(value), 3)


class ControlEngine:
    """Deterministic evaluator; owns only hysteresis state (streaks,
    cooldowns, assumed arm/credit), all keyed on tick counters."""

    def __init__(self, cfg: ControlConfig) -> None:
        self.cfg = cfg
        self._arm_streak = 0
        self._relax_streak = 0
        self._reb_streak = 0
        # last tick a decision of each kind was emitted (cooldown anchor);
        # dry-run still advances these so an intent is logged once per
        # cooldown window instead of every tick
        self._last_emit: dict[str, int] = {}
        # engine's view of the armed state: in dry-run the real floor never
        # moves, so track emitted intent to keep the log shape comparable
        self._armed = False
        self._assumed_credit: Optional[int] = None

    # -- helpers -----------------------------------------------------------

    def _cooled(self, kind: str, tick: int, window: int) -> bool:
        last = self._last_emit.get(kind)
        return last is None or tick - last >= window

    def _emit(self, decisions: list, inp: ControlInputs, kind: str,
              action: dict, inputs: dict) -> None:
        decisions.append({
            "id": f"d{inp.tick}.{len(decisions)}",
            "tick": inp.tick,
            "kind": kind,
            "action": action,
            "inputs": inputs,
        })
        self._last_emit[kind.split(".", 1)[0]] = inp.tick

    # -- evaluation --------------------------------------------------------

    def evaluate(self, inp: ControlInputs) -> tuple[list, int]:
        """One control tick -> (decisions, suppressed-trigger count)."""
        decisions: list = []
        suppressed = 0
        suppressed += self._admission(decisions, inp)
        suppressed += self._rebalance(decisions, inp)
        suppressed += self._prefetch(decisions, inp)
        return decisions, suppressed

    def _admission(self, decisions: list, inp: ControlInputs) -> int:
        cfg = self.cfg
        if inp.enter_throttle <= 0:
            return 0
        source = "trend"
        net = inp.net_rate
        if inp.forecast_net_rate is not None:
            source = "forecast"
            net = inp.forecast_net_rate
        projected = inp.gate_total + cfg.horizon_ticks * inp.interval_s * net
        armed = self._armed or inp.floor >= STAGE_THROTTLE
        snap = {
            "gate_total": inp.gate_total,
            "projected": _r(projected),
            "net_rate": _r(net),
            "source": source,
            "stage": inp.stage,
            "enter_throttle": inp.enter_throttle,
            "exit_throttle": inp.exit_throttle,
        }
        if not armed:
            self._relax_streak = 0
            if inp.stage < STAGE_THROTTLE and projected > inp.enter_throttle:
                self._arm_streak += 1
                if self._arm_streak < cfg.arm_ticks:
                    return 0
                if not self._cooled("admission", inp.tick, cfg.cooldown_ticks):
                    return 1
                credit = inp.publish_credit
                shrunk = (max(cfg.credit_min, int(credit * cfg.credit_factor))
                          if credit > 0 else 0)
                self._emit(decisions, inp, "admission.prearm",
                           {"floor": STAGE_THROTTLE,
                            "publish_credit": shrunk}, snap)
                self._armed = True
                self._assumed_credit = credit
            else:
                self._arm_streak = 0
            return 0
        # armed: look for the exit band
        self._arm_streak = 0
        if (projected <= inp.exit_throttle
                and inp.gate_total <= inp.exit_throttle):
            self._relax_streak += 1
            if self._relax_streak < cfg.arm_ticks:
                return 0
            if not self._cooled("admission", inp.tick, cfg.cooldown_ticks):
                return 1
            restore = (self._assumed_credit
                       if self._assumed_credit is not None
                       else inp.publish_credit)
            self._emit(decisions, inp, "admission.relax",
                       {"floor": 0, "publish_credit": restore}, snap)
            self._armed = False
            self._assumed_credit = None
            self._relax_streak = 0
        else:
            self._relax_streak = 0
        return 0

    def _rebalance(self, decisions: list, inp: ControlInputs) -> int:
        cfg = self.cfg
        if not inp.peer_loads:
            self._reb_streak = 0
            return 0
        loads = dict(inp.peer_loads)
        loads[inp.node] = inp.self_load
        mean = sum(loads.values()) / len(loads)
        join = inp.join_target
        if join is not None and join in inp.peer_loads:
            # join-triggered rebalance: a fresh member carries nothing, so
            # the divergence gate would sit silent until this node is
            # already hot — seed the joiner with the busiest movable queue
            # immediately (cooldown still applies; the service bounds the
            # window)
            if not self._cooled("rebalance", inp.tick,
                                cfg.rebalance_cooldown_ticks):
                return 1
            movable = [q for q in inp.queues if q.movable]
            if not movable:
                return 1
            queue = max(movable,
                        key=lambda q: (q.publish_rate + q.deliver_rate,
                                       q.vhost, q.name))
            self._emit(decisions, inp, "rebalance.move",
                       {"vhost": queue.vhost, "name": queue.name,
                        "target": join, "join": True},
                       {"self_load": _r(inp.self_load),
                        "mean_load": _r(mean),
                        "queue_rate": _r(queue.publish_rate
                                         + queue.deliver_rate),
                        "loads": {n: _r(v) for n, v in sorted(loads.items())}})
            self._reb_streak = 0
            return 0
        if mean < cfg.rebalance_min_rate or \
                inp.self_load <= cfg.rebalance_ratio * mean:
            self._reb_streak = 0
            return 0
        self._reb_streak += 1
        if self._reb_streak < cfg.arm_ticks:
            return 0
        if not self._cooled("rebalance", inp.tick,
                            cfg.rebalance_cooldown_ticks):
            return 1
        movable = [q for q in inp.queues if q.movable]
        if not movable:
            return 1
        # busiest movable queue -> least-loaded peer; name tiebreaks keep
        # the pick deterministic when rates are equal
        queue = max(movable, key=lambda q: (q.publish_rate + q.deliver_rate,
                                            q.vhost, q.name))
        target = min(inp.peer_loads.items(), key=lambda kv: (kv[1], kv[0]))[0]
        self._emit(decisions, inp, "rebalance.move",
                   {"vhost": queue.vhost, "name": queue.name,
                    "target": target},
                   {"self_load": _r(inp.self_load), "mean_load": _r(mean),
                    "ratio": _r(cfg.rebalance_ratio),
                    "queue_rate": _r(queue.publish_rate + queue.deliver_rate),
                    "loads": {n: _r(v) for n, v in sorted(loads.items())}})
        self._reb_streak = 0
        return 0

    def _prefetch(self, decisions: list, inp: ControlInputs) -> int:
        cfg = self.cfg
        credit = inp.consume_credit
        if credit is None or not inp.queues:
            return 0
        active = [q for q in inp.queues
                  if q.consumers > 0 and q.deliver_rate > 0.0]
        if not active:
            return 0
        lagging = [q for q in active
                   if q.ack_rate < cfg.prefetch_lag * q.deliver_rate]
        keeping = [q for q in active
                   if q.ack_rate >= cfg.prefetch_keep * q.deliver_rate]
        backlog = any(
            (q.forecast_depth if q.forecast_depth is not None else q.depth)
            > 0 for q in active)
        if lagging:
            new = max(cfg.prefetch_min, credit // 2)
            reason = "ack-lag"
        elif keeping and backlog and not lagging:
            new = min(cfg.prefetch_max, credit * 2)
            reason = "backlog-headroom"
        else:
            return 0
        if new == credit:
            return 0
        if not self._cooled("prefetch", inp.tick, cfg.prefetch_cooldown_ticks):
            return 1
        worst = min(active, key=lambda q: (
            q.ack_rate / q.deliver_rate if q.deliver_rate else 1.0,
            q.vhost, q.name))
        self._emit(decisions, inp, "prefetch.tune",
                   {"consume_credit": new},
                   {"reason": reason, "current": credit,
                    "queue": f"{worst.vhost}/{worst.name}",
                    "deliver_rate": _r(worst.deliver_rate),
                    "ack_rate": _r(worst.ack_rate)})
        return 0

    def snapshot(self) -> dict:
        return {
            "armed": self._armed,
            "arm_streak": self._arm_streak,
            "relax_streak": self._relax_streak,
            "rebalance_streak": self._reb_streak,
            "last_emit": dict(self._last_emit),
        }
