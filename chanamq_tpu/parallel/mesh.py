"""Mesh construction and sharding rules for the forecaster.

Sharding plan (2D mesh, axes ("dp", "tp")):
- batch: P("dp") on the leading axis — pure data parallelism;
- attention qkv kernel [d, 3d]: P(None, "tp") — heads split across tp;
- attention proj [d, d]:        P("tp", None) — row-split, GSPMD inserts the
  reduce-scatter/all-reduce on the output;
- mlp w1 [d, 4d]: P(None, "tp") column-split; w2 [4d, d]: P("tp", None)
  row-split (the classic Megatron pairing, expressed purely as shardings);
- layernorm scales / biases / embeddings: replicated.

Everything else (collective insertion, overlap) is GSPMD's job — we only
annotate. See /opt/skills/guides/pallas_guide.md + the scaling-book recipe.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.forecaster import ForecasterConfig, Params


def make_mesh(n_devices: Optional[int] = None, tp: Optional[int] = None) -> Mesh:
    """Build a (dp, tp) mesh over the first n_devices devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if tp is None:
        # widest tp that divides the device count while leaving dp >= 2,
        # so the dryrun exercises both axes (and their collectives)
        tp = 1
        for cand in (4, 2):
            if n % cand == 0 and n // cand >= 2:
                tp = cand
                break
    dp = n // tp
    mesh_devices = mesh_utils.create_device_mesh((dp, tp), devices=devices)
    return Mesh(mesh_devices, ("dp", "tp"))


def _spec_for(name: str) -> P:
    if name.endswith("attn/qkv") or name.endswith("mlp/w1"):
        return P(None, "tp")
    if name.endswith("attn/proj") or name.endswith("mlp/w2"):
        return P("tp", None)
    return P()  # replicated: norms, biases, embed, pos, head


def param_shardings(mesh: Mesh, params: Params) -> dict[str, NamedSharding]:
    return {name: NamedSharding(mesh, _spec_for(name)) for name in params}


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))


def make_sharded_train_step(
    mesh: Mesh, cfg: ForecasterConfig, step_fn: Callable
) -> Callable:
    """jit the train step with explicit in/out shardings over the mesh."""
    dummy = {name: None for name in _param_names(cfg)}
    p_shard = {name: NamedSharding(mesh, _spec_for(name)) for name in dummy}
    b_shard = (batch_sharding(mesh), batch_sharding(mesh))
    return jax.jit(
        step_fn,
        in_shardings=(p_shard, p_shard, b_shard),
        out_shardings=(p_shard, p_shard, NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )


def _param_names(cfg: ForecasterConfig) -> list[str]:
    names = ["embed/kernel", "embed/bias", "pos", "out/kernel", "out/bias"]
    for layer in range(cfg.n_layers):
        pre = f"layer{layer}"
        names += [
            f"{pre}/ln1/scale", f"{pre}/ln2/scale",
            f"{pre}/attn/qkv", f"{pre}/attn/proj",
            f"{pre}/mlp/w1", f"{pre}/mlp/w2",
        ]
    return names


def place_params(mesh: Mesh, params: Params) -> Params:
    """Device-put a param tree with its shardings (host -> mesh)."""
    return {
        name: jax.device_put(value, NamedSharding(mesh, _spec_for(name)))
        for name, value in params.items()
    }


def place_batch(mesh: Mesh, batch: Any):
    """Device-put a (x, y) batch tuple dp-sharded on the leading axis."""
    return tuple(jax.device_put(part, batch_sharding(mesh)) for part in batch)


def place(mesh: Mesh, params: Params, batch: Any):
    """Device-put params/batch with their shardings (host -> mesh)."""
    return place_params(mesh, params), place_batch(mesh, batch)
