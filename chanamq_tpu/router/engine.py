"""TensorRouter: batched publish routing over compiled binding tables.

The broker owns one TensorRouter (``chana.mq.router.enabled``). The
connection read loop, instead of routing each fused publish inline, defers
eligible messages into a per-connection buffer and flushes the WHOLE read
batch through ``Broker.flush_deferred_publishes`` -> ``route_pending``
here: one compiled-table lookup per exchange and one jitted kernel call
per exchange per flush, instead of one trie walk per message.

Consistency model (why deferral is safe):

- Deferral only happens between awaits of a single connection's read-batch
  processing, and every path that can publish, run a generic AMQP command,
  release confirms, or close the connection flushes the buffer FIRST
  (synchronously — the single-node publish path never awaits). The event
  loop is single-threaded, so no other connection's topology mutation can
  interleave with an unflushed buffer: the vhost/exchange state observed
  at ``defer_ok`` time is still live at flush time.
- ``Broker.invalidate_routes(vhost, exchange)`` drops exactly that
  exchange's compiled snapshot (or all of them for bulk mutations);
  recompilation is lazy, at the next flush that routes through it, under a
  monotonically increasing generation counter. Snapshots are immutable —
  a flush in progress keeps routing against the snapshot it resolved.
- Exchanges the compiler rejects (``Uncompilable``) and sub-``min-batch``
  kernel batches fall back to the exchange's Python matcher — the always
  available, always-correct oracle. ``chana.mq.router.verify`` cross-checks
  every kernel result against the oracle and prefers the oracle on any
  mismatch (counted in ``router_parity_mismatches``).
"""

from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Optional

from . import compile as rcompile

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.broker import Broker

log = logging.getLogger("chanamq.router")

_DEFERRABLE_TYPES = ("direct", "fanout", "topic", "headers")

# resolved (vhost, name-set) -> [Queue] memo cap; cleared on invalidate
_QUEUE_CACHE_CAP = 8192


def _classify_topic(pattern: str, queues, exact: dict, always: set,
                    wild: dict) -> None:
    """Sort one topic pattern into the universal closure shape: exact
    string key, unconditional, or genuine wildcard row."""
    toks = pattern.split(".")
    nhash = toks.count("#")
    if nhash == 0 and "*" not in toks:
        exact.setdefault(pattern, set()).update(queues)
    elif toks == ["#"]:
        always.update(queues)
    elif nhash > 1:
        raise rcompile.Uncompilable("multi-# pattern")
    else:
        wild.setdefault(pattern, set()).update(queues)


class TensorRouter:
    """Per-broker batch router over compiled binding tables."""

    def __init__(
        self,
        broker: "Broker",
        *,
        backend: str = "jax",
        min_batch: int = 16,
        max_wildcards: int = 512,
        max_queues: int = 4096,
        verify: bool = False,
    ) -> None:
        self.broker = broker
        self.backend = backend if backend in ("jax", "python") else "jax"
        self.min_batch = max(1, min_batch)
        self.max_wildcards = max_wildcards
        self.max_queues = max_queues
        self.verify = verify
        self.generation = 0
        # (vhost, exchange) -> CompiledExchange | str (uncompilable reason)
        self._compiled: dict = {}
        # (vhost, exchange) -> bool deferral decision memo
        self._defer: dict = {}
        # (vhost, frozenset-of-names) -> [Queue]
        self._queue_cache: dict = {}
        # closure dependency edges: (vhost, member-exchange) -> set of
        # (vhost, root-exchange) whose flattened snapshot embeds the
        # member's bindings — a bind/unbind anywhere in a compiled e2e
        # graph must drop every root built over it
        self._closure_deps: dict = {}

    # -- invalidation ------------------------------------------------------

    def invalidate(self, vhost: Optional[str] = None,
                   exchange: Optional[str] = None) -> None:
        """Topology changed. With a (vhost, exchange) only that snapshot is
        dropped (dirty-exchange batching: untouched tables keep their
        compiled form); bulk mutations drop everything. Either way the
        deferral decisions and resolved-queue memo reset — they embed
        exchange structure and live Queue objects."""
        self._defer.clear()
        self._queue_cache.clear()
        if vhost is None or exchange is None:
            self._compiled.clear()
            self._closure_deps.clear()
        else:
            self._compiled.pop((vhost, exchange), None)
            # dependent invalidation: every flattened e2e root whose
            # closure walked through this exchange recompiles lazily too
            roots = self._closure_deps.pop((vhost, exchange), None)
            if roots:
                for root_key in roots:
                    self._compiled.pop(root_key, None)

    # -- deferral decision (publish hot path) ------------------------------

    def defer_ok(self, vhost_name: str, exchange_name: str) -> bool:
        """Whether a fused publish to this exchange may be deferred into
        the batch buffer. Memoized; any invalidate() clears the memo. The
        structural checks guarantee a later flush cannot raise: the
        exchange exists, is externally publishable, and carries none of
        the semantics (alternate exchange, e2e bindings) the batch path
        doesn't implement."""
        key = (vhost_name, exchange_name)
        ok = self._defer.get(key)
        if ok is None:
            ok = self._defer[key] = self._compute_defer(
                vhost_name, exchange_name)
        return ok

    def _compute_defer(self, vhost_name: str, exchange_name: str) -> bool:
        if exchange_name == "":
            return False  # default exchange: the dict hit is already optimal
        vhost = self.broker.vhosts.get(vhost_name)
        if vhost is None:
            return False
        exchange = vhost.exchanges.get(exchange_name)
        if exchange is None or exchange.internal:
            return False
        if exchange.alternate is not None:
            return False
        if exchange.type not in _DEFERRABLE_TYPES:
            return False
        if exchange.ex_matcher is not None:
            # e2e source: defer only when the graph closure flattened into
            # a compiled snapshot (semantics PR) — an uncompilable closure
            # keeps the inline per-message walk, since a batched fallback
            # would just re-run the same walk later
            return self._get_compiled(
                vhost, vhost_name, exchange_name) is not None
        return True

    # -- batch routing -----------------------------------------------------

    def _get_compiled(self, vhost, vhost_name: str, exchange_name: str):
        key = (vhost_name, exchange_name)
        comp = self._compiled.get(key)
        if comp is None:
            exchange = vhost.exchanges[exchange_name]
            self.generation += 1
            metrics = self.broker.metrics
            metrics.router_generation = self.generation
            try:
                if exchange.ex_matcher is not None:
                    comp = self._compile_closure(
                        vhost, vhost_name, exchange_name)
                else:
                    comp = rcompile.compile_exchange(
                        exchange.type, exchange.matcher.bindings(),
                        generation=self.generation,
                        max_wildcards=self.max_wildcards,
                        max_queues=self.max_queues)
                metrics.router_compiles += 1
            except rcompile.Uncompilable as exc:
                comp = exc.reason
                log.debug("exchange %s/%s not tensorizable: %s",
                          vhost_name, exchange_name, exc.reason)
            self._compiled[key] = comp
        return None if isinstance(comp, str) else comp

    # -- e2e closure flattening --------------------------------------------

    def _compile_closure(self, vhost, vhost_name: str, root: str):
        """Flatten `root`'s exchange-to-exchange graph closure into one
        compiled table: a publish routed through the snapshot reaches the
        exact queue set the runtime breadth-first walk would, with zero
        per-message graph traversal. Each hop's predicate composes by
        CONJUNCTION (every hop re-matches the ORIGINAL routing key), so
        only trivially-chainable graphs flatten — always-match edges
        (fanout, lone '#') merge the sub-closure wholesale, exact-key
        edges evaluate it at the known key, and a genuine-wildcard edge
        composes with exact/always sub-entries only. Anything else
        (wildcard-over-wildcard, headers, alternate-exchange fallbacks,
        recovered cycles) raises Uncompilable and stays on the walk."""
        exact: dict[str, set] = {}
        always: set = set()
        wild: dict[str, set] = {}
        self._flatten(vhost, vhost_name, root, root,
                      exact, always, wild, (root,))
        return rcompile.compile_effective(
            exact, always, wild, generation=self.generation,
            max_wildcards=self.max_wildcards, max_queues=self.max_queues)

    def _flatten(self, vhost, vhost_name: str, root: str, name: str,
                 exact: dict, always: set, wild: dict, path: tuple) -> None:
        # dependency edge FIRST (even for dangling/failing members): an
        # Uncompilable verdict cached for the root must also be dropped
        # when any member's bindings change
        self._closure_deps.setdefault((vhost_name, name), set()).add(
            (vhost_name, root))
        ex = vhost.exchanges.get(name)
        if ex is None:
            return  # dangling e2e target: routes nowhere until redeclared
        if ex.alternate is not None:
            raise rcompile.Uncompilable("alternate exchange in e2e closure")
        kind = ex.type
        if kind == "headers":
            raise rcompile.Uncompilable("headers exchange in e2e closure")
        if kind not in ("direct", "fanout", "topic"):
            raise rcompile.Uncompilable(f"e2e closure over {kind!r}")
        for key, queue, _args in ex.matcher.bindings():
            if kind == "fanout":
                always.add(queue)
            elif kind == "direct":
                exact.setdefault(key, set()).add(queue)
            else:
                _classify_topic(key, (queue,), exact, always, wild)
        if ex.ex_matcher is None:
            return
        for pkey, dst, _args in ex.ex_matcher.bindings():
            if dst in path:
                # a pre-guard (recovered) cycle: the walk dedups it, a
                # flat table cannot represent it
                raise rcompile.Uncompilable("cycle in e2e closure")
            s_exact: dict[str, set] = {}
            s_always: set = set()
            s_wild: dict[str, set] = {}
            self._flatten(vhost, vhost_name, root, dst,
                          s_exact, s_always, s_wild, path + (dst,))
            toks = pkey.split(".") if kind == "topic" else None
            if kind == "fanout" or (toks is not None and toks == ["#"]):
                # always-match hop: sub-closure merges wholesale
                always.update(s_always)
                for k, qs in s_exact.items():
                    exact.setdefault(k, set()).update(qs)
                for pat, qs in s_wild.items():
                    wild.setdefault(pat, set()).update(qs)
            elif kind == "direct" or ("#" not in toks and "*" not in toks):
                # exact-key hop: evaluate the sub-closure at the one key
                # that can traverse it (compile-time, never per-message)
                qs = set(s_exact.get(pkey, ())) | s_always
                for pat, sq in s_wild.items():
                    if rcompile.topic_match(pat, pkey):
                        qs |= sq
                if qs:
                    exact.setdefault(pkey, set()).update(qs)
            else:
                # genuine wildcard hop: p AND sub-predicate composes only
                # when the sub side is trivial (TRUE or an exact key)
                if toks.count("#") > 1:
                    raise rcompile.Uncompilable("multi-# e2e pattern")
                if s_always:
                    _classify_topic(pkey, s_always, exact, always, wild)
                for k, qs in s_exact.items():
                    if rcompile.topic_match(pkey, k):
                        exact.setdefault(k, set()).update(qs)
                if s_wild:
                    raise rcompile.Uncompilable(
                        "wildcard-over-wildcard e2e chain")

    def _queues(self, vhost_name: str, vhost, names) -> list:
        """Resolve a routed name-set to live Queue objects, memoized per
        distinct set (fan-out traffic repeats a handful of sets)."""
        cache = self._queue_cache
        key = (vhost_name, names)
        queues = cache.get(key)
        if queues is None:
            vq = vhost.queues
            queues = [vq[n] for n in names if n in vq]
            if len(cache) >= _QUEUE_CACHE_CAP:
                cache.clear()
            cache[key] = queues
        return queues

    def route_pending(self, vhost_name: str, entries: list):
        """Route one deferred flush. ``entries`` rows are
        ``(exchange, routing_key, props, body, header_raw, exrk_raw,
        confirmed)``; returns ``(queues_per_entry, t0_ns, t1_ns)`` with the
        batch routing window for ROUTE span stamping."""
        t0 = time.perf_counter_ns()
        metrics = self.broker.metrics
        vhost = self.broker.vhosts[vhost_name]
        out: list = [None] * len(entries)
        # group by exchange: one compiled snapshot + one kernel call each
        groups: dict[str, list[int]] = {}
        for idx, entry in enumerate(entries):
            groups.setdefault(entry[0], []).append(idx)
        for exchange_name, idxs in groups.items():
            compiled = self._get_compiled(vhost, vhost_name, exchange_name)
            use_kernel = compiled is not None and (
                compiled.kernel_rows == 0 or len(idxs) >= self.min_batch)
            if not use_kernel:
                # Python fallback: uncompilable table, or a batch too
                # small to amortize the kernel dispatch. An e2e source
                # falls back to the full graph walk, not the single-hop
                # matcher — the closure IS the exchange's route set.
                metrics.router_fallback_msgs += len(idxs)
                exchange = vhost.exchanges[exchange_name]
                if exchange.ex_matcher is not None:
                    for idx in idxs:
                        entry = entries[idx]
                        names = frozenset(vhost.route(
                            exchange_name, entry[1], entry[2].headers))
                        out[idx] = self._queues(vhost_name, vhost, names)
                else:
                    matcher = exchange.matcher
                    for idx in idxs:
                        entry = entries[idx]
                        names = frozenset(
                            matcher.route(entry[1], entry[2].headers))
                        out[idx] = self._queues(vhost_name, vhost, names)
                continue
            items = [(entries[i][1], entries[i][2].headers) for i in idxs]
            name_sets = rcompile.route_batch(compiled, items, self.backend)
            if self.verify:
                exchange = vhost.exchanges[exchange_name]
                if exchange.ex_matcher is not None:
                    # live oracle for a flattened closure is the runtime
                    # graph walk itself
                    def _oracle(k, h, _n=exchange_name):
                        return vhost.route(_n, k, h)
                else:
                    _oracle = exchange.matcher.route
                for pos, (key, headers) in enumerate(items):
                    oracle = _oracle(key, headers)
                    if set(name_sets[pos]) != oracle:
                        metrics.router_parity_mismatches += 1
                        log.error(
                            "router parity mismatch on %s/%s key=%r: "
                            "kernel=%r oracle=%r", vhost_name, exchange_name,
                            key, sorted(name_sets[pos]), sorted(oracle))
                        name_sets[pos] = frozenset(oracle)
            metrics.router_batches += 1
            metrics.router_batch_msgs += len(idxs)
            metrics.router_batch_size.observe_us(len(idxs))
            for idx, names in zip(idxs, name_sets):
                out[idx] = self._queues(vhost_name, vhost, names)
        return out, t0, time.perf_counter_ns()
