"""SLO engine: rolling error budgets + multi-window multi-burn-rate alerts.

The Google SRE alerting shape over the broker's own telemetry: each
declarative :class:`SLOSpec` names a service-level indicator (a good/bad
event stream the telemetry tick derives from counters it already samples),
an objective (e.g. 0.999 → a 0.1% error budget), and two window *pairs* —
a fast pair (5 m / 1 h at 1 s ticks) that catches budget-torching
incidents in minutes, and a slow pair (6 h / 3 d) that catches slow leaks.
A pair alerts only when BOTH its windows burn above the pair's threshold:
the long window proves the burn is sustained, the short window proves it
is still happening (so the alert also clears promptly).

burn_rate(window) = (bad/total over the window) / (1 - objective) —
1.0 means the budget is being consumed exactly at the rate that exhausts
it at the window's end; 14.4 (the classic fast threshold) exhausts a
30-day budget in 2 days.

Determinism (the AlertEngine/ControlEngine contract): ``evaluate(tick,
samples)`` is a pure function of the per-tick good/bad samples — no wall
clock, no randomness — so the seeded soaks assert firings exactly and the
burn-rate math is testable against a hand-computed oracle.

Memory: windows are tracked as cumulative (good, bad) totals in two fixed
rings — per-tick for the last ``FINE`` ticks (exact for the fast pair) and
one sample every ``COARSE`` ticks for the long horizon (a 3-day window at
1 s ticks costs 2 float64 rings of 8192, not a 259200-slot buffer; the
window edge quantizes to the coarse stride, deterministically).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

FINE = 4096          # exact per-tick cumulative history
COARSE = 64          # stride of the coarse cumulative ring
COARSE_SLOTS = 8192  # * COARSE ticks = 524288-tick horizon (~6 d at 1 s)

#: SLI kinds the telemetry tick knows how to sample (slo/__init__.py).
SLI_KINDS = (
    "publish-success",    # good=accepted publishes, bad=refused+returned
    "delivery-success",   # good=deliveries, bad=dead-lettered+expired
    "readiness",          # one sample per tick: /admin/health ready?
    "delivery-latency",   # one sample per tick: delta p99 <= threshold?
    "federation-lag",     # one sample per tick: link lag <= record budget?
)


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over an SLI stream.

    Window fields are in ticks; ``from_config``/``specs_from_json`` scale
    from wall durations by the telemetry interval. ``threshold_ms`` only
    applies to latency SLIs (a tick is bad when its delta p99 exceeds it).
    """

    name: str
    sli: str
    objective: float = 0.999
    threshold_ms: float = 250.0
    fast_windows: tuple = (300, 3600)      # (short, long) ticks
    slow_windows: tuple = (21600, 259200)
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    budget_window: int = 259200
    severity: str = "critical"
    #: tenant-scoped objective (chanamq_tpu/tenancy/): the spec evaluates
    #: the tenant's OWN good/bad stream (sample key "<sli>@<tenant>") with
    #: an independent error budget; None = node-wide stream, as before
    tenant: Optional[str] = None

    def sample_key(self) -> str:
        """The key this spec reads from the per-tick samples dict."""
        return self.sli if self.tenant is None else f"{self.sli}@{self.tenant}"

    def as_dict(self) -> dict:
        return {
            "name": self.name, "sli": self.sli,
            "objective": self.objective, "threshold_ms": self.threshold_ms,
            "fast_windows": list(self.fast_windows),
            "slow_windows": list(self.slow_windows),
            "fast_burn": self.fast_burn, "slow_burn": self.slow_burn,
            "budget_window": self.budget_window, "severity": self.severity,
            "tenant": self.tenant,
        }


def default_slos(interval_s: float = 1.0, *, objective: float = 0.999,
                 latency_ms: float = 250.0, fast_burn: float = 14.4,
                 slow_burn: float = 6.0) -> list[SLOSpec]:
    """The built-in objectives, window durations scaled to ticks."""
    def ticks(seconds: float) -> int:
        return max(1, int(round(seconds / max(interval_s, 1e-9))))

    fast = (ticks(300), ticks(3600))
    slow = (ticks(21600), ticks(259200))
    budget = ticks(259200)
    common = dict(fast_windows=fast, slow_windows=slow,
                  budget_window=budget, fast_burn=fast_burn,
                  slow_burn=slow_burn)
    return [
        SLOSpec("publish-availability", "publish-success",
                objective=objective, **common),
        SLOSpec("delivery-success", "delivery-success",
                objective=objective, **common),
        SLOSpec("readiness", "readiness", objective=objective, **common),
        SLOSpec("delivery-latency-p99", "delivery-latency",
                objective=max(0.99, objective - 0.009),
                threshold_ms=latency_ms, **common),
    ]


def specs_from_json(raw: list, interval_s: float = 1.0) -> list[SLOSpec]:
    """Build specs from POST /admin/slo/configure (or config-file) dicts.
    Window fields may be given in seconds (``*_windows_s``) or ticks."""
    def ticks(seconds: float) -> int:
        return max(1, int(round(float(seconds) / max(interval_s, 1e-9))))

    specs = []
    for item in raw:
        if not isinstance(item, dict) or not item.get("name"):
            raise ValueError("each spec needs at least a name")
        sli = item.get("sli", "publish-success")
        if sli not in SLI_KINDS:
            raise ValueError(f"unknown sli {sli!r} (have {SLI_KINDS})")
        tenant = item.get("tenant")
        if tenant is not None and (not isinstance(tenant, str) or not tenant):
            raise ValueError(
                f"spec {item['name']!r}: tenant must be a non-empty string")
        kw = dict(
            name=str(item["name"]), sli=sli,
            objective=float(item.get("objective", 0.999)),
            threshold_ms=float(item.get("threshold_ms", 250.0)),
            fast_burn=float(item.get("fast_burn", 14.4)),
            slow_burn=float(item.get("slow_burn", 6.0)),
            severity=str(item.get("severity", "critical")),
            tenant=tenant,
        )
        if "fast_windows_s" in item:
            kw["fast_windows"] = tuple(ticks(s) for s in item["fast_windows_s"])
        elif "fast_windows" in item:
            kw["fast_windows"] = tuple(int(t) for t in item["fast_windows"])
        if "slow_windows_s" in item:
            kw["slow_windows"] = tuple(ticks(s) for s in item["slow_windows_s"])
        elif "slow_windows" in item:
            kw["slow_windows"] = tuple(int(t) for t in item["slow_windows"])
        if "budget_window_s" in item:
            kw["budget_window"] = ticks(item["budget_window_s"])
        elif "budget_window" in item:
            kw["budget_window"] = int(item["budget_window"])
        spec = SLOSpec(**kw)
        for pair in (spec.fast_windows, spec.slow_windows):
            if len(pair) != 2 or pair[0] > pair[1]:
                raise ValueError(
                    f"spec {spec.name!r}: window pair must be "
                    f"(short, long) with short <= long, got {pair}")
        if not 0.0 < spec.objective < 1.0:
            raise ValueError(
                f"spec {spec.name!r}: objective must be in (0, 1)")
        specs.append(spec)
    return specs


class _Track:
    """Cumulative good/bad rings for one spec (see module docstring)."""

    __slots__ = ("cum_good", "cum_bad", "fine", "coarse", "start_tick")

    def __init__(self) -> None:
        self.cum_good = 0.0
        self.cum_bad = 0.0
        # column 0 = cumulative good, column 1 = cumulative bad
        self.fine = np.zeros((FINE, 2), dtype=np.float64)
        self.coarse = np.zeros((COARSE_SLOTS, 2), dtype=np.float64)
        self.start_tick: Optional[int] = None

    def push(self, tick: int, good: float, bad: float) -> None:
        if self.start_tick is None:
            self.start_tick = tick
        self.cum_good += good
        self.cum_bad += bad
        self.fine[tick % FINE, 0] = self.cum_good
        self.fine[tick % FINE, 1] = self.cum_bad
        if tick % COARSE == 0:
            self.coarse[(tick // COARSE) % COARSE_SLOTS, 0] = self.cum_good
            self.coarse[(tick // COARSE) % COARSE_SLOTS, 1] = self.cum_bad

    def _cum_at(self, tick: int, target: int) -> tuple[float, float]:
        """Cumulative totals as of tick ``target`` (quantized to the
        coarse stride beyond the fine horizon; (0, 0) before start)."""
        if self.start_tick is None or target < self.start_tick:
            return (0.0, 0.0)
        if tick - target < FINE:
            row = self.fine[target % FINE]
            return (float(row[0]), float(row[1]))
        ctarget = (target // COARSE) * COARSE
        if ctarget < self.start_tick or tick - ctarget >= COARSE * COARSE_SLOTS:
            return (0.0, 0.0)
        row = self.coarse[(ctarget // COARSE) % COARSE_SLOTS]
        return (float(row[0]), float(row[1]))

    def window(self, tick: int, window: int) -> tuple[float, float]:
        """(good, bad) deltas over the trailing ``window`` ticks."""
        g0, b0 = self._cum_at(tick, tick - window)
        return (self.cum_good - g0, self.cum_bad - b0)


class SLOEngine:
    """Tick-driven burn-rate evaluator over declarative SLO specs."""

    HISTORY = 256  # retained burn/clear events for /admin/slo

    def __init__(self, specs: list[SLOSpec]) -> None:
        if not specs:
            raise ValueError("SLOEngine needs at least one spec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.specs = list(specs)
        self._tracks = {s.name: _Track() for s in self.specs}
        # (spec name, pair name) -> info dict while the pair is burning
        self.firing: dict[tuple, dict] = {}
        self.history: deque = deque(maxlen=self.HISTORY)
        self.fired_total = 0
        self.cleared_total = 0
        self.violations: dict[str, int] = {s.name: 0 for s in self.specs}
        self.tick = 0

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def burn_rate(good: float, bad: float, objective: float) -> float:
        total = good + bad
        if total <= 0.0:
            return 0.0
        return (bad / total) / max(1.0 - objective, 1e-12)

    def budget_remaining(self, spec: SLOSpec) -> float:
        """Fraction of the error budget left over the budget window:
        1.0 = untouched, 0.0 = exhausted, negative = overspent."""
        track = self._tracks[spec.name]
        good, bad = track.window(self.tick, spec.budget_window)
        total = good + bad
        if total <= 0.0:
            return 1.0
        allowed = (1.0 - spec.objective) * total
        return 1.0 - bad / max(allowed, 1e-12)

    def evaluate(self, tick: int,
                 samples: dict[str, tuple[float, float]]) -> list[dict]:
        """One tick. ``samples`` maps SLI kind -> (good, bad) deltas for
        this tick. Returns burn/clear transition events in deterministic
        spec order. Pure: same tick series in, same events out."""
        self.tick = tick
        events: list[dict] = []
        for spec in self.specs:
            track = self._tracks[spec.name]
            good, bad = samples.get(spec.sample_key(), (0.0, 0.0))
            track.push(tick, float(good), float(bad))
            for pair_name, windows, threshold in (
                ("fast", spec.fast_windows, spec.fast_burn),
                ("slow", spec.slow_windows, spec.slow_burn),
            ):
                b_short = self.burn_rate(
                    *track.window(tick, windows[0]), spec.objective)
                b_long = self.burn_rate(
                    *track.window(tick, windows[1]), spec.objective)
                fkey = (spec.name, pair_name)
                burning = b_short > threshold and b_long > threshold
                if burning and fkey not in self.firing:
                    info = {
                        "slo": spec.name, "pair": pair_name,
                        "sli": spec.sli, "severity": spec.severity,
                        "tenant": spec.tenant,
                        "burn_short": round(b_short, 4),
                        "burn_long": round(b_long, 4),
                        "threshold": threshold,
                        "windows": list(windows),
                        "budget_remaining": round(
                            self.budget_remaining(spec), 6),
                        "since_tick": tick,
                    }
                    self.firing[fkey] = info
                    self.fired_total += 1
                    self.violations[spec.name] += 1
                    events.append({"event": "burn", **info})
                elif fkey in self.firing:
                    if b_short <= threshold:
                        # the short window recovered: the burn stopped
                        info = self.firing.pop(fkey)
                        self.cleared_total += 1
                        events.append({
                            "event": "clear", **info,
                            "burn_short": round(b_short, 4),
                            "burn_long": round(b_long, 4),
                            "cleared_tick": tick,
                            "ticks": tick - info["since_tick"],
                        })
                    else:
                        self.firing[fkey]["burn_short"] = round(b_short, 4)
                        self.firing[fkey]["burn_long"] = round(b_long, 4)
        self.history.extend(events)
        return events

    # -- snapshots ---------------------------------------------------------

    def slo_status(self, spec: SLOSpec) -> dict:
        track = self._tracks[spec.name]
        tick = self.tick
        burns = {}
        for pair_name, windows in (("fast", spec.fast_windows),
                                   ("slow", spec.slow_windows)):
            for label, w in zip(("short", "long"), windows):
                good, bad = track.window(tick, w)
                burns[f"{pair_name}_{label}"] = {
                    "window_ticks": w,
                    "good": good, "bad": bad,
                    "burn_rate": round(
                        self.burn_rate(good, bad, spec.objective), 4),
                }
        return {
            **spec.as_dict(),
            "budget_remaining": round(self.budget_remaining(spec), 6),
            "burn": burns,
            "burning": sorted(
                pair for (name, pair) in self.firing if name == spec.name),
            "violations_total": self.violations[spec.name],
            "totals": {"good": track.cum_good, "bad": track.cum_bad},
        }

    def snapshot(self) -> dict:
        return {
            "tick": self.tick,
            "slos": [self.slo_status(s) for s in self.specs],
            "firing": sorted(
                self.firing.values(),
                key=lambda i: (i["slo"], i["pair"])),
            "fired_total": self.fired_total,
            "cleared_total": self.cleared_total,
            "recent": list(self.history),
        }

    def readiness_stamp(self) -> dict:
        """The compact block stamped onto the /admin/health payload."""
        return {
            "burning": sorted(
                f"{name}:{pair}" for (name, pair) in self.firing),
            "budget_remaining": {
                s.name: round(self.budget_remaining(s), 6)
                for s in self.specs
            },
        }
