"""SQLite StoreService — the durable backend, with group commit.

Capability parity with the reference's CassandraOpService
(chana-mq-server .../store/cassandra/CassandraOpService.scala:46-756): same
schema shape — message blobs + refcount, queue log keyed (queue, offset),
queue metas with a lastConsumed watermark, unacks, binds, vhosts, and
*_deleted archival copies on queue delete (pendingDeleteQueue,
CassandraOpService.scala:561-604).

Design difference from the reference, on purpose. The reference's `execute`
blocked its calling thread per operation while pretending to be async
(CassandraOpService.scala:753-755) — SURVEY.md §7.3 flags that as its
weakest scar. Here the store **group-commits**:

- every operation is enqueued synchronously (strict program order) and
  returns an asyncio.Future;
- one dedicated writer thread drains the queue in batches: all ops queued
  while the previous batch was committing run inside ONE transaction with
  ONE commit, each op isolated by a savepoint;
- an op's future resolves only after the COMMIT that covers it, so awaiting
  any write is a durability barrier — and `flush()` gives callers a barrier
  over everything enqueued so far (the broker awaits it before releasing
  publisher confirms).

Reads ride the same FIFO queue, so read-your-writes ordering holds without
blocking the event loop. TTL expiry is a stored expire_at timestamp filtered
on read (the analogue of Cassandra row TTL).
"""

from __future__ import annotations

import asyncio
import json
import logging
import sqlite3
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional, TypeVar

from .api import (
    StoredExchange, StoredMessage, StoredQueue, StoreService,
    is_replica_vhost,
)

log = logging.getLogger("chanamq.store")

T = TypeVar("T")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS msgs (
  id INTEGER PRIMARY KEY, header BLOB, body BLOB,
  exchange TEXT, routing_key TEXT, refer_count INTEGER, ttl_ms INTEGER
);
CREATE TABLE IF NOT EXISTS queue_metas (
  vhost TEXT, name TEXT, durable INTEGER, exclusive_ INTEGER,
  auto_delete INTEGER, ttl_ms INTEGER, last_consumed INTEGER,
  arguments TEXT, PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS queue_msgs (
  vhost TEXT, queue TEXT, offset INTEGER, msg_id INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, offset)
);
CREATE TABLE IF NOT EXISTS queue_unacks (
  vhost TEXT, queue TEXT, msg_id INTEGER, offset INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, msg_id)
);
CREATE TABLE IF NOT EXISTS exchanges (
  vhost TEXT, name TEXT, type TEXT, durable INTEGER,
  auto_delete INTEGER, internal INTEGER, arguments TEXT,
  PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS binds (
  vhost TEXT, exchange TEXT, queue TEXT, routing_key TEXT, arguments TEXT,
  PRIMARY KEY (vhost, exchange, queue, routing_key)
);
CREATE TABLE IF NOT EXISTS exchange_binds (
  vhost TEXT, exchange TEXT, destination TEXT, routing_key TEXT, arguments TEXT,
  PRIMARY KEY (vhost, exchange, destination, routing_key)
);
CREATE TABLE IF NOT EXISTS stream_segments (
  vhost TEXT, queue TEXT, base_offset INTEGER, last_offset INTEGER,
  first_ts_ms INTEGER, last_ts_ms INTEGER, size_bytes INTEGER, blob BLOB,
  PRIMARY KEY (vhost, queue, base_offset)
);
CREATE TABLE IF NOT EXISTS stream_cursors (
  vhost TEXT, queue TEXT, name TEXT, committed_offset INTEGER,
  PRIMARY KEY (vhost, queue, name)
);
CREATE TABLE IF NOT EXISTS vhosts (name TEXT PRIMARY KEY, active INTEGER);
CREATE TABLE IF NOT EXISTS cluster_kv (key TEXT PRIMARY KEY, value INTEGER);
CREATE TABLE IF NOT EXISTS queue_metas_deleted (
  vhost TEXT, name TEXT, meta TEXT, PRIMARY KEY (vhost, name)
);
CREATE TABLE IF NOT EXISTS queue_msgs_deleted (
  vhost TEXT, queue TEXT, offset INTEGER, msg_id INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, offset)
);
CREATE TABLE IF NOT EXISTS queue_unacks_deleted (
  vhost TEXT, queue TEXT, msg_id INTEGER, offset INTEGER,
  body_size INTEGER, expire_at_ms INTEGER,
  PRIMARY KEY (vhost, queue, msg_id)
);
"""


class SqliteStore(StoreService):
    def __init__(self, path: str = ":memory:",
                 synchronous: str = "NORMAL") -> None:
        self.path = path
        # durability tier (PRAGMA synchronous, config
        # chana.mq.store.synchronous): NORMAL (default) survives process
        # crashes — a COMMIT is in the OS page cache and the WAL replays
        # after SIGKILL — but a POWER loss can roll back recently-committed
        # transactions (confirms included). FULL fsyncs every group commit:
        # power-loss durable, at a large cost to persistent throughput.
        # The reference inherited whatever its Cassandra cluster was
        # configured for; here the knob is explicit.
        if synchronous.upper() not in ("OFF", "NORMAL", "FULL", "EXTRA"):
            raise ValueError(f"bad synchronous level {synchronous!r}")
        self.synchronous = synchronous.upper()
        self._db: Optional[sqlite3.Connection] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # single writer thread => strict FIFO op ordering
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="store")
        # group-commit state (event-loop side)
        self._pending: list[
            tuple[Callable[[sqlite3.Connection], Any], asyncio.Future, bool, int]
        ] = []
        self._flush_scheduled = False
        self._batch_in_flight = False
        # failure attribution: every op gets a sequence number at enqueue;
        # failed ops (op error or commit failure) record their seq so a
        # durability barrier can raise for exactly the ops it covers.
        # Callers that promise durability for a specific window (publisher
        # confirms, cluster push replies) capture mark() around their
        # enqueues and pass those intervals to flush() — so one publisher's
        # failed insert never errors (or silently passes under) another
        # publisher's barrier (the reference's scar this engine was built to
        # beat, CassandraOpService.scala:753-755).
        self._op_seq = 0
        self._failed_seqs: list[int] = []
        self._failed_floor = 0  # seqs <= floor were dropped from the list:
        # any interval reaching below it reports failure conservatively
        self._reported_mark = 0  # consume-once watermark for global flush()

    # -- group-commit engine ----------------------------------------------

    def _enqueue(self, fn, fut, guard: bool) -> None:
        """Append one op entry and schedule a kick — the single place the
        seq-increment / append / coalescing-kick dance lives."""
        self._op_seq += 1
        self._pending.append((fn, fut, guard, self._op_seq))
        if not self._flush_scheduled:
            # coalesce everything submitted this loop tick into one batch
            self._flush_scheduled = True
            loop = self._loop or asyncio.get_running_loop()
            loop.call_soon(self._kick)

    def _submit(
        self, fn: Callable[[sqlite3.Connection], T], guard: bool = True
    ) -> "asyncio.Future[T]":
        """Enqueue one op; returns a future resolved after the commit that
        covers it. Enqueue order == execution order (program order).

        guard=False marks ops whose body is a single SQL statement (or one
        executemany): a lone statement is atomic by itself, so the per-op
        savepoint wrapper is skipped. Multi-statement ops keep the savepoint
        so a mid-op failure can't leave a partial effect in the batch."""
        loop = self._loop or asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._enqueue(fn, fut, guard)
        return fut

    def _submit_nowait(self, fn: Callable[[sqlite3.Connection], Any],
                       guard: bool = False) -> None:
        """Enqueue a fire-and-forget op: same FIFO queue and sequence
        numbering as _submit (so durability-barrier attribution covers it),
        but no future/callback machinery — the per-message hot path
        (message blob + queue-log inserts) pays only a lambda and a list
        append. Failures are logged and recorded for barriers."""
        self._enqueue(fn, None, guard)

    def _submit_row(self, sql: str, params: tuple) -> None:
        """Fire-and-forget single-row statement, enqueued as (sql, params)
        data instead of a callable: the writer thread coalesces rows with
        the same SQL into one executemany per batch (one savepoint per
        group), cutting per-row statement overhead on the per-message hot
        ops.

        ORDERING CONTRACT — weaker than _submit/_submit_nowait: rows with
        the SAME SQL keep their relative order, and all rows execute before
        the next callable op, but rows with DIFFERENT SQL may reorder
        against each other within a batch. Only route a statement through
        here if it commutes with every other _submit_row statement — in
        practice: the statements must target distinct tables (today: msgs
        vs queue_msgs). A same-table insert+delete pair would silently
        swap; keep such ops on _submit/_submit_nowait."""
        self._enqueue((sql, params), None, False)

    def _kick(self) -> None:
        self._flush_scheduled = False
        self._maybe_dispatch_batch()

    def _maybe_dispatch_batch(self) -> None:
        if self._batch_in_flight or not self._pending or self._db is None:
            return
        self._batch_in_flight = True
        batch = self._pending
        self._pending = []
        db = self._db
        loop = self._loop
        assert loop is not None

        def run_batch() -> None:
            results: list[tuple[asyncio.Future, Any, Optional[BaseException], int]] = []
            try:
                # IMMEDIATE: take the write lock up front so multi-process
                # users (nodes sharing a db file) serialize cleanly
                db.execute("BEGIN IMMEDIATE")
            except Exception as exc:  # pragma: no cover - disk/lock failure
                loop.call_soon_threadsafe(
                    self._batch_done, [(f, None, exc, s) for _, f, _, s in batch])
                return
            # _submit_row ops accumulate into per-SQL groups, one
            # executemany + savepoint per group. Rows with different SQL
            # target different tables (or distinct keys) and commute; any
            # opaque callable op is a reorder barrier — groups flush before
            # it runs, so row-vs-callable order is preserved exactly. On a
            # group failure every row in it reports failed — conservative
            # (the rollback undoes all of them) and barrier-correct.
            pending_rows: dict[str, tuple[list, list]] = {}

            def flush_rows() -> None:
                for sql, (rows, seqs) in pending_rows.items():
                    try:
                        db.execute("SAVEPOINT op")
                        db.executemany(sql, rows)
                        db.execute("RELEASE SAVEPOINT op")
                        results.extend((None, None, None, s) for s in seqs)
                    except Exception as exc:
                        try:
                            db.execute("ROLLBACK TO SAVEPOINT op")
                            db.execute("RELEASE SAVEPOINT op")
                        except Exception:  # pragma: no cover
                            pass
                        results.extend((None, None, exc, s) for s in seqs)
                pending_rows.clear()

            for fn, fut, guard, seq in batch:
                if type(fn) is tuple:
                    entry = pending_rows.get(fn[0])
                    if entry is None:
                        entry = pending_rows[fn[0]] = ([], [])
                    entry[0].append(fn[1])
                    entry[1].append(seq)
                    continue
                if pending_rows:
                    flush_rows()
                if guard:
                    try:
                        db.execute("SAVEPOINT op")
                        res = fn(db)
                        db.execute("RELEASE SAVEPOINT op")
                        results.append((fut, res, None, seq))
                    except Exception as exc:
                        try:
                            db.execute("ROLLBACK TO SAVEPOINT op")
                            db.execute("RELEASE SAVEPOINT op")
                        except Exception:  # pragma: no cover
                            pass
                        results.append((fut, None, exc, seq))
                else:
                    try:
                        results.append((fut, fn(db), None, seq))
                    except Exception as exc:
                        results.append((fut, None, exc, seq))
            if pending_rows:
                flush_rows()
            try:
                db.execute("COMMIT")
            except Exception as exc:  # pragma: no cover - disk failure
                try:
                    db.execute("ROLLBACK")
                except Exception:
                    pass
                results = [(f, None, exc, s) for f, _, _, s in results]
            loop.call_soon_threadsafe(self._batch_done, results)

        self._executor.submit(run_batch)

    _FAILED_CAP = 4096

    def _batch_done(
        self, results: list[tuple[asyncio.Future, Any, Optional[BaseException], int]]
    ) -> None:
        self._batch_in_flight = False
        for fut, res, exc, seq in results:
            if exc is not None:
                self._failed_seqs.append(seq)
                if len(self._failed_seqs) > self._FAILED_CAP:
                    # bound the list; barriers reaching below the floor
                    # report failure conservatively
                    self._failed_floor = max(
                        self._failed_floor, self._failed_seqs.pop(0))
            if fut is None:  # _submit_nowait op
                if exc is not None:
                    # count it, don't just log it: error_count feeds the
                    # telemetry store-error window and readiness reasons —
                    # a store silently failing fire-and-forget writes must
                    # flip /admin/health, not only a log line
                    self.error_count = getattr(self, "error_count", 0) + 1
                    log.error("background store write failed: %r", exc)
                continue
            if fut.cancelled():
                continue
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(res)
        # ops accumulated while the batch was committing -> next batch
        self._maybe_dispatch_batch()

    def mark(self) -> int:
        """Sequence number of the last op enqueued. Capture around a group
        of enqueues and pass the (before, after] interval to flush() for
        per-caller failure attribution."""
        return self._op_seq

    def _failures_in(self, intervals: list[tuple[int, int]]) -> bool:
        for s0, s1 in intervals:
            if s0 < self._failed_floor:
                return True
            for s in reversed(self._failed_seqs):
                if s0 < s <= s1:
                    return True
        return False

    def _unreported_failures(self, barrier_mark: int) -> bool:
        had = self._failures_in([(self._reported_mark, barrier_mark)])
        if barrier_mark > self._reported_mark:
            self._reported_mark = barrier_mark
        return had

    def flush(self, intervals: Optional[list[tuple[int, int]]] = None):
        """Durability barrier: awaitable resolving once every op enqueued so
        far has been committed.

        intervals=None (global barrier — shutdown, tests): raises if any
        write failed that no previous global barrier reported — a confirm
        released after this barrier must not paper over a failed persistent
        insert that was enqueued fire-and-forget, including one whose batch
        already completed while the event loop was busy elsewhere (the idle
        fast path checks too).

        intervals=[(mark_before, mark_after), ...] (attributed barrier —
        publisher confirms, cluster push replies): raises iff a failed op's
        seq falls inside one of the caller's own enqueue windows, so
        connection A's barrier can neither consume nor trip over
        connection B's failure. An empty list means the caller enqueued
        nothing it needs committed: resolves immediately, no barrier.

        Cheap when idle (already-resolved future)."""
        loop = self._loop or asyncio.get_running_loop()
        if intervals is not None and not intervals:
            fut: asyncio.Future = loop.create_future()
            fut.set_result(None)
            return fut
        barrier_mark = self._op_seq

        def covered_failure() -> bool:
            if intervals is not None:
                return self._failures_in(intervals)
            return self._unreported_failures(barrier_mark)

        if not self._pending and not self._batch_in_flight:
            fut = loop.create_future()
            if covered_failure():
                fut.set_exception(RuntimeError(
                    "store write failed before this durability barrier"))
            else:
                fut.set_result(None)
            return fut
        barrier = self._submit(lambda db: None, guard=False)

        async def wait() -> None:
            await barrier
            # FIFO resolution: every op enqueued before the barrier has been
            # resolved (and its failure recorded) by the time it resolves
            if covered_failure():
                raise RuntimeError(
                    "store write failed under this durability barrier")

        return wait()

    # -- lifecycle ---------------------------------------------------------

    async def open(self) -> None:
        self._loop = asyncio.get_running_loop()

        def _open() -> sqlite3.Connection:
            # isolation_level=None: WE manage transactions (BEGIN/COMMIT per
            # batch); the stdlib's implicit transactions would fight that.
            db = sqlite3.connect(
                self.path, check_same_thread=False, isolation_level=None)
            db.execute("PRAGMA journal_mode=WAL")
            db.execute(f"PRAGMA synchronous={self.synchronous}")
            db.execute("PRAGMA busy_timeout=10000")
            db.executescript(_SCHEMA)
            return db

        self._db = await self._loop.run_in_executor(self._executor, _open)
        # ops may have queued while opening
        self._maybe_dispatch_batch()

    async def close(self) -> None:
        if self._db is not None:
            try:
                await self.flush()
            except Exception:
                pass
            db = self._db
            self._db = None
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(self._executor, db.close)
        self._executor.shutdown(wait=False)

    async def approx_data_bytes(self) -> Optional[int]:
        """Live data pages × page size. page_count alone would be wrong for
        the gate: DELETEs never shrink the file (pages go to the freelist
        for reuse), so the gauge must subtract freelist pages or a drained
        store would stay 'full' forever and the gate would never reopen."""
        if self._db is None:
            return None

        def q(db: sqlite3.Connection) -> int:
            page_size = db.execute("PRAGMA page_size").fetchone()[0]
            page_count = db.execute("PRAGMA page_count").fetchone()[0]
            freelist = db.execute("PRAGMA freelist_count").fetchone()[0]
            return (page_count - freelist) * page_size

        return await self._submit(q)

    # -- messages ---------------------------------------------------------

    _SQL_INSERT_MSG = "INSERT OR REPLACE INTO msgs VALUES (?,?,?,?,?,?,?)"

    @staticmethod
    def _msg_row(msg: StoredMessage) -> tuple:
        return (msg.id, msg.properties_raw, msg.body, msg.exchange,
                msg.routing_key, msg.refer_count, msg.ttl_ms)

    def insert_message(self, msg: StoredMessage):
        row = self._msg_row(msg)
        return self._submit(
            lambda db: db.execute(self._SQL_INSERT_MSG, row), guard=False)

    def insert_message_nowait(self, msg: StoredMessage) -> None:
        self._submit_row(self._SQL_INSERT_MSG, self._msg_row(msg))

    @staticmethod
    def _row_to_message(row) -> StoredMessage:
        return StoredMessage(
            id=row[0], properties_raw=row[1], body=row[2], exchange=row[3],
            routing_key=row[4], refer_count=row[5], ttl_ms=row[6],
        )

    # stay under SQLITE_MAX_VARIABLE_NUMBER for giant recovery batches
    _IN_CHUNK = 900

    async def select_message(self, msg_id: int) -> Optional[StoredMessage]:
        row = await self._submit(lambda db: db.execute(
            "SELECT * FROM msgs WHERE id=?", (msg_id,)).fetchone(), guard=False)
        return self._row_to_message(row) if row is not None else None

    async def _select_in(self, columns: str, msg_ids: list[int]) -> list:
        rows: list = []
        for start in range(0, len(msg_ids), self._IN_CHUNK):
            chunk = msg_ids[start:start + self._IN_CHUNK]
            qmarks = ",".join("?" * len(chunk))
            rows += await self._submit(lambda db, c=chunk, q=qmarks: db.execute(
                f"SELECT {columns} FROM msgs WHERE id IN ({q})", c).fetchall(),
                guard=False)
        return rows

    async def select_messages(self, msg_ids: list[int]) -> dict[int, StoredMessage]:
        if not msg_ids:
            return {}
        rows = await self._select_in("*", msg_ids)
        return {row[0]: self._row_to_message(row) for row in rows}

    async def select_message_metas(self, msg_ids: list[int]) -> dict[int, StoredMessage]:
        if not msg_ids:
            return {}
        rows = await self._select_in(
            "id, header, NULL, exchange, routing_key, refer_count, ttl_ms",
            msg_ids)
        return {row[0]: self._row_to_message(row) for row in rows}

    def delete_message(self, msg_id: int):
        return self._submit(lambda db: db.execute(
            "DELETE FROM msgs WHERE id=?", (msg_id,)), guard=False)

    def delete_messages(self, msg_ids: list[int]):
        return self._submit(lambda db: db.executemany(
            "DELETE FROM msgs WHERE id=?", [(m,) for m in msg_ids]),
            guard=False)

    def update_message_refer_count(self, msg_id: int, count: int):
        return self._submit(lambda db: db.execute(
            "UPDATE msgs SET refer_count=? WHERE id=?", (count, msg_id)), guard=False)

    # -- queue meta -------------------------------------------------------

    def insert_queue_meta(self, q: StoredQueue):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO queue_metas VALUES (?,?,?,?,?,?,?,?)",
            (q.vhost, q.name, int(q.durable), int(q.exclusive),
             int(q.auto_delete), q.ttl_ms, q.last_consumed,
             json.dumps(q.arguments)),
        ), guard=False)

    async def select_queue(self, vhost: str, name: str) -> Optional[StoredQueue]:
        def q(db: sqlite3.Connection):
            meta = db.execute(
                "SELECT * FROM queue_metas WHERE vhost=? AND name=?",
                (vhost, name)).fetchone()
            if meta is None:
                return None
            msgs = db.execute(
                "SELECT offset, msg_id, body_size, expire_at_ms FROM queue_msgs "
                "WHERE vhost=? AND queue=? AND offset>? ORDER BY offset",
                (vhost, name, meta[6])).fetchall()
            unacks = db.execute(
                "SELECT msg_id, offset, body_size, expire_at_ms FROM queue_unacks "
                "WHERE vhost=? AND queue=?", (vhost, name)).fetchall()
            return meta, msgs, unacks

        out = await self._submit(q)
        if out is None:
            return None
        meta, msgs, unacks = out
        return StoredQueue(
            vhost=meta[0], name=meta[1], durable=bool(meta[2]),
            exclusive=bool(meta[3]), auto_delete=bool(meta[4]), ttl_ms=meta[5],
            last_consumed=meta[6], arguments=json.loads(meta[7] or "{}"),
            msgs=[tuple(m) for m in msgs],
            unacks={u[0]: (u[1], u[2], u[3]) for u in unacks},
        )

    async def all_queues(self, vhost: Optional[str] = None) -> list[StoredQueue]:
        def q(db: sqlite3.Connection):
            if vhost is None:
                return db.execute("SELECT vhost, name FROM queue_metas").fetchall()
            return db.execute(
                "SELECT vhost, name FROM queue_metas WHERE vhost=?", (vhost,)
            ).fetchall()

        names = await self._submit(q)
        out = []
        for vh, name in names:
            if is_replica_vhost(vh):
                continue  # passive replica copies never recover as live
            sq = await self.select_queue(vh, name)
            if sq:
                out.append(sq)
        return out

    # -- queue log --------------------------------------------------------

    _SQL_INSERT_QUEUE_MSG = (
        "INSERT OR REPLACE INTO queue_msgs VALUES (?,?,?,?,?,?)")

    def insert_queue_msg(self, vhost, queue, offset, msg_id, body_size, expire_at_ms):
        row = (vhost, queue, offset, msg_id, body_size, expire_at_ms)
        return self._submit(
            lambda db: db.execute(self._SQL_INSERT_QUEUE_MSG, row), guard=False)

    def insert_queue_msg_nowait(
            self, vhost, queue, offset, msg_id, body_size, expire_at_ms) -> None:
        self._submit_row(
            self._SQL_INSERT_QUEUE_MSG,
            (vhost, queue, offset, msg_id, body_size, expire_at_ms))

    def delete_queue_msg(self, vhost, queue, offset):
        return self._submit(lambda db: db.execute(
            "DELETE FROM queue_msgs WHERE vhost=? AND queue=? AND offset=?",
            (vhost, queue, offset)), guard=False)

    async def iter_queue_msgs(self, vhost, queue, after_offset, limit):
        rows = await self._submit(lambda db: db.execute(
            "SELECT offset, msg_id, body_size, expire_at_ms FROM queue_msgs "
            "WHERE vhost=? AND queue=? AND offset>? ORDER BY offset LIMIT ?",
            (vhost, queue, after_offset, limit)).fetchall())
        return [tuple(r) for r in rows]

    def replace_queue_msgs(self, vhost, queue, msgs):
        def w(db: sqlite3.Connection):
            db.execute(
                "DELETE FROM queue_msgs WHERE vhost=? AND queue=?",
                (vhost, queue))
            db.executemany(
                self._SQL_INSERT_QUEUE_MSG,
                [(vhost, queue, o, m, s, e) for (o, m, s, e) in msgs])

        return self._submit(w)

    def replace_queue_unacks(self, vhost, queue, unacks):
        def w(db: sqlite3.Connection):
            db.execute(
                "DELETE FROM queue_unacks WHERE vhost=? AND queue=?",
                (vhost, queue))
            db.executemany(
                "INSERT OR REPLACE INTO queue_unacks VALUES (?,?,?,?,?,?)",
                [(vhost, queue, m, o, s, e) for (m, o, s, e) in unacks])

        return self._submit(w)

    # -- watermark + unacks ------------------------------------------------

    def update_queue_last_consumed(self, vhost, queue, last_consumed):
        def w(db: sqlite3.Connection):
            db.execute(
                "UPDATE queue_metas SET last_consumed=? WHERE vhost=? AND name=?",
                (last_consumed, vhost, queue))
            db.execute(
                "DELETE FROM queue_msgs WHERE vhost=? AND queue=? AND offset<=?",
                (vhost, queue, last_consumed))

        return self._submit(w)

    @staticmethod
    def _insert_queue_unacks_op(vhost, queue, unacks):
        return lambda db: db.executemany(
            "INSERT OR REPLACE INTO queue_unacks VALUES (?,?,?,?,?,?)",
            [(vhost, queue, m, o, s, e) for (m, o, s, e) in unacks])

    def insert_queue_unacks(self, vhost, queue, unacks):
        return self._submit(
            self._insert_queue_unacks_op(vhost, queue, unacks), guard=False)

    def insert_queue_unacks_nowait(self, vhost, queue, unacks) -> None:
        self._submit_nowait(self._insert_queue_unacks_op(vhost, queue, unacks))

    def delete_queue_msgs_offsets(self, vhost, queue, offsets):
        return self._submit(lambda db: db.executemany(
            "DELETE FROM queue_msgs WHERE vhost=? AND queue=? AND offset=?",
            [(vhost, queue, o) for o in offsets]), guard=False)

    def delete_queue_unacks(self, vhost, queue, msg_ids):
        return self._submit(lambda db: db.executemany(
            "DELETE FROM queue_unacks WHERE vhost=? AND queue=? AND msg_id=?",
            [(vhost, queue, m) for m in msg_ids]), guard=False)

    # -- delete/archive ----------------------------------------------------

    def archive_queue(self, vhost, queue):
        def w(db: sqlite3.Connection):
            meta = db.execute(
                "SELECT * FROM queue_metas WHERE vhost=? AND name=?",
                (vhost, queue)).fetchone()
            if meta:
                db.execute(
                    "INSERT OR REPLACE INTO queue_metas_deleted VALUES (?,?,?)",
                    (vhost, queue, json.dumps(list(meta))))
            db.execute(
                "INSERT OR REPLACE INTO queue_msgs_deleted "
                "SELECT * FROM queue_msgs WHERE vhost=? AND queue=?",
                (vhost, queue))
            db.execute(
                "INSERT OR REPLACE INTO queue_unacks_deleted "
                "SELECT * FROM queue_unacks WHERE vhost=? AND queue=?",
                (vhost, queue))

        return self._submit(w)

    def delete_queue(self, vhost, queue):
        def w(db: sqlite3.Connection):
            db.execute("DELETE FROM queue_metas WHERE vhost=? AND name=?", (vhost, queue))
            db.execute("DELETE FROM queue_msgs WHERE vhost=? AND queue=?", (vhost, queue))
            db.execute("DELETE FROM queue_unacks WHERE vhost=? AND queue=?", (vhost, queue))

        return self._submit(w)

    def purge_queue_msgs(self, vhost, queue):
        return self._submit(lambda db: db.execute(
            "DELETE FROM queue_msgs WHERE vhost=? AND queue=?", (vhost, queue)), guard=False)

    # -- stream segments + cursors -----------------------------------------

    def insert_stream_segment(self, vhost, queue, base_offset, last_offset,
                              first_ts_ms, last_ts_ms, size_bytes, blob):
        row = (vhost, queue, base_offset, last_offset, first_ts_ms,
               last_ts_ms, size_bytes, blob)
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO stream_segments VALUES (?,?,?,?,?,?,?,?)",
            row), guard=False)

    async def select_stream_segment(self, vhost, queue, base_offset):
        row = await self._submit(lambda db: db.execute(
            "SELECT blob FROM stream_segments "
            "WHERE vhost=? AND queue=? AND base_offset=?",
            (vhost, queue, base_offset)).fetchone(), guard=False)
        return row[0] if row is not None else None

    async def stream_segment_metas(self, vhost, queue):
        rows = await self._submit(lambda db: db.execute(
            "SELECT base_offset, last_offset, first_ts_ms, last_ts_ms, "
            "size_bytes FROM stream_segments WHERE vhost=? AND queue=? "
            "ORDER BY base_offset", (vhost, queue)).fetchall(), guard=False)
        return [tuple(r) for r in rows]

    def delete_stream_segments(self, vhost, queue, base_offsets):
        return self._submit(lambda db: db.executemany(
            "DELETE FROM stream_segments "
            "WHERE vhost=? AND queue=? AND base_offset=?",
            [(vhost, queue, b) for b in base_offsets]), guard=False)

    def update_stream_cursor(self, vhost, queue, name, committed_offset):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO stream_cursors VALUES (?,?,?,?)",
            (vhost, queue, name, committed_offset)), guard=False)

    async def select_stream_cursors(self, vhost, queue):
        rows = await self._submit(lambda db: db.execute(
            "SELECT name, committed_offset FROM stream_cursors "
            "WHERE vhost=? AND queue=?", (vhost, queue)).fetchall(),
            guard=False)
        return {r[0]: r[1] for r in rows}

    def delete_stream_data(self, vhost, queue):
        def w(db: sqlite3.Connection):
            db.execute("DELETE FROM stream_segments WHERE vhost=? AND queue=?",
                       (vhost, queue))
            db.execute("DELETE FROM stream_cursors WHERE vhost=? AND queue=?",
                       (vhost, queue))

        return self._submit(w)

    # -- exchanges + binds -------------------------------------------------

    def insert_exchange(self, ex: StoredExchange):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO exchanges VALUES (?,?,?,?,?,?,?)",
            (ex.vhost, ex.name, ex.type, int(ex.durable), int(ex.auto_delete),
             int(ex.internal), json.dumps(ex.arguments)),
        ), guard=False)

    async def select_exchange(self, vhost, name) -> Optional[StoredExchange]:
        def q(db: sqlite3.Connection):
            row = db.execute(
                "SELECT * FROM exchanges WHERE vhost=? AND name=?",
                (vhost, name)).fetchone()
            if row is None:
                return None
            binds = db.execute(
                "SELECT routing_key, queue, arguments FROM binds "
                "WHERE vhost=? AND exchange=?", (vhost, name)).fetchall()
            ex_binds = db.execute(
                "SELECT routing_key, destination, arguments FROM exchange_binds "
                "WHERE vhost=? AND exchange=?", (vhost, name)).fetchall()
            return row, binds, ex_binds

        out = await self._submit(q)
        if out is None:
            return None
        row, binds, ex_binds = out
        return StoredExchange(
            vhost=row[0], name=row[1], type=row[2], durable=bool(row[3]),
            auto_delete=bool(row[4]), internal=bool(row[5]),
            arguments=json.loads(row[6] or "{}"),
            binds=[(b[0], b[1], json.loads(b[2]) if b[2] else None) for b in binds],
            ex_binds=[(b[0], b[1], json.loads(b[2]) if b[2] else None)
                      for b in ex_binds],
        )

    async def all_exchanges(self, vhost: Optional[str] = None) -> list[StoredExchange]:
        def q(db: sqlite3.Connection):
            if vhost is None:
                return db.execute("SELECT vhost, name FROM exchanges").fetchall()
            return db.execute(
                "SELECT vhost, name FROM exchanges WHERE vhost=?", (vhost,)
            ).fetchall()

        names = await self._submit(q)
        out = []
        for vh, name in names:
            ex = await self.select_exchange(vh, name)
            if ex:
                out.append(ex)
        return out

    def delete_exchange(self, vhost, name):
        def w(db: sqlite3.Connection):
            db.execute("DELETE FROM exchanges WHERE vhost=? AND name=?", (vhost, name))
            db.execute("DELETE FROM binds WHERE vhost=? AND exchange=?", (vhost, name))
            db.execute("DELETE FROM exchange_binds WHERE vhost=? AND exchange=?",
                       (vhost, name))

        return self._submit(w)

    def insert_exchange_bind(self, vhost, source, destination, routing_key, arguments):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO exchange_binds VALUES (?,?,?,?,?)",
            (vhost, source, destination, routing_key,
             json.dumps(arguments) if arguments else None),
        ), guard=False)

    def delete_exchange_bind(self, vhost, source, destination, routing_key):
        return self._submit(lambda db: db.execute(
            "DELETE FROM exchange_binds "
            "WHERE vhost=? AND exchange=? AND destination=? AND routing_key=?",
            (vhost, source, destination, routing_key)), guard=False)

    def delete_exchange_binds_dest(self, vhost, destination):
        return self._submit(lambda db: db.execute(
            "DELETE FROM exchange_binds WHERE vhost=? AND destination=?",
            (vhost, destination)), guard=False)

    def insert_bind(self, vhost, exchange, queue, routing_key, arguments):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO binds VALUES (?,?,?,?,?)",
            (vhost, exchange, queue, routing_key,
             json.dumps(arguments) if arguments else None),
        ), guard=False)

    def delete_bind(self, vhost, exchange, queue, routing_key):
        return self._submit(lambda db: db.execute(
            "DELETE FROM binds WHERE vhost=? AND exchange=? AND queue=? AND routing_key=?",
            (vhost, exchange, queue, routing_key)), guard=False)

    def delete_queue_binds(self, vhost, queue):
        return self._submit(lambda db: db.execute(
            "DELETE FROM binds WHERE vhost=? AND queue=?", (vhost, queue)), guard=False)

    # -- WAL engine support (chanamq_tpu/wal/) -----------------------------
    # The write-ahead wrapper keeps its checkpoint watermark in cluster_kv,
    # needs a real fsync of the database at checkpoint time, and runs
    # stream-segment maintenance (key compaction + tier offload) through
    # blob-level helpers that the plain store API doesn't expose.

    async def get_kv(self, key: str) -> Optional[int]:
        def q(db: sqlite3.Connection) -> Optional[int]:
            row = db.execute(
                "SELECT value FROM cluster_kv WHERE key=?", (key,)).fetchone()
            return int(row[0]) if row is not None else None

        return await self._submit(q, guard=False)

    def put_kv(self, key: str, value: int):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO cluster_kv VALUES (?,?)",
            (key, value)), guard=False)

    def worker_id_floor(self, n: int):
        """Replay-only: next_worker_id = max(current, n). WAL recovery uses
        it so an id allocated just before a crash is never re-issued."""
        def w(db: sqlite3.Connection) -> None:
            db.execute(
                "INSERT OR IGNORE INTO cluster_kv VALUES ('next_worker_id', 0)")
            db.execute(
                "UPDATE cluster_kv SET value=? "
                "WHERE key='next_worker_id' AND value<?", (n, n))

        return self._submit(w)

    async def checkpoint_sync(self) -> None:
        """fsync the database file. Under synchronous=NORMAL, SQLite only
        fsyncs at WAL checkpoints — the wrapper calls this before
        truncating its own segments, so a power cut can't eat index state
        the WAL no longer covers. A checkpoint cannot run inside a
        transaction, so this rides the writer executor directly (the
        single-threaded executor serializes it between group commits)."""
        db = self._db
        if db is None:
            return
        loop = self._loop or asyncio.get_running_loop()
        await loop.run_in_executor(
            self._executor,
            lambda: db.execute("PRAGMA wal_checkpoint(TRUNCATE)").fetchone())

    async def stream_segment_index(self) -> list:
        """Whole-store segment index for maintenance sweeps:
        (vhost, queue, base_offset, size_bytes, has_blob) rows."""
        rows = await self._submit(lambda db: db.execute(
            "SELECT vhost, queue, base_offset, size_bytes, "
            "blob IS NOT NULL FROM stream_segments "
            "ORDER BY vhost, queue, base_offset").fetchall(), guard=False)
        return [tuple(r) for r in rows]

    def evict_stream_blob(self, vhost, queue, base_offset):
        """Tier offload: drop the blob bytes, keep the index row."""
        return self._submit(lambda db: db.execute(
            "UPDATE stream_segments SET blob=NULL "
            "WHERE vhost=? AND queue=? AND base_offset=?",
            (vhost, queue, base_offset)), guard=False)

    def replace_stream_segment_blob(self, vhost, queue, base_offset,
                                    blob, size_bytes):
        """Key compaction: swap a sealed segment's bytes in place (offsets
        inside the blob are preserved; last_offset stays)."""
        return self._submit(lambda db: db.execute(
            "UPDATE stream_segments SET blob=?, size_bytes=? "
            "WHERE vhost=? AND queue=? AND base_offset=?",
            (blob, size_bytes, vhost, queue, base_offset)), guard=False)

    async def queue_arguments(self, vhost, name) -> Optional[dict]:
        row = await self._submit(lambda db: db.execute(
            "SELECT arguments FROM queue_metas WHERE vhost=? AND name=?",
            (vhost, name)).fetchone(), guard=False)
        if row is None:
            return None
        return json.loads(row[0] or "{}")

    def allocate_worker_id(self):
        # runs inside the batch's BEGIN IMMEDIATE transaction, so the
        # read-modify-write is atomic across processes sharing the file
        def w(db: sqlite3.Connection) -> int:
            db.execute(
                "INSERT OR IGNORE INTO cluster_kv VALUES ('next_worker_id', 0)")
            db.execute(
                "UPDATE cluster_kv SET value = value + 1 "
                "WHERE key = 'next_worker_id'")
            row = db.execute(
                "SELECT value FROM cluster_kv WHERE key = 'next_worker_id'"
            ).fetchone()
            return int(row[0])

        return self._submit(w)

    # -- vhosts ------------------------------------------------------------

    def insert_vhost(self, name: str, active: bool = True):
        return self._submit(lambda db: db.execute(
            "INSERT OR REPLACE INTO vhosts VALUES (?,?)", (name, int(active))), guard=False)

    async def all_vhosts(self) -> list[tuple[str, bool]]:
        rows = await self._submit(
            lambda db: db.execute("SELECT name, active FROM vhosts").fetchall(),
            guard=False)
        return [(r[0], bool(r[1])) for r in rows]

    def delete_vhost(self, name: str):
        return self._submit(lambda db: db.execute(
            "DELETE FROM vhosts WHERE name=?", (name,)), guard=False)
