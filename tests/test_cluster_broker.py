"""Multi-node broker cluster tests: location transparency, remote consume,
metadata replication, and the HA contract (durable messages survive node
death by recovery from the shared store — reference README.md:47-49,
SURVEY.md §3.6)."""

import asyncio

import pytest

from chanamq_tpu.amqp.properties import BasicProperties
from chanamq_tpu.broker.server import BrokerServer
from chanamq_tpu.client import AMQPClient
from chanamq_tpu.cluster.node import ClusterNode
from chanamq_tpu.store.sqlite import SqliteStore

pytestmark = pytest.mark.asyncio

PERSISTENT = BasicProperties(delivery_mode=2)


class Node:
    """One in-process broker node with its cluster extension."""

    def __init__(self, server: BrokerServer, cluster: ClusterNode) -> None:
        self.server = server
        self.cluster = cluster

    @property
    def port(self) -> int:
        return self.server.bound_port

    @property
    def name(self) -> str:
        return self.cluster.name

    async def stop(self) -> None:
        await self.cluster.stop()
        await self.server.stop()


async def start_node(store_path, seeds, failure_timeout_s=0.8) -> Node:
    server = BrokerServer(host="127.0.0.1", port=0, heartbeat_s=0,
                          store=SqliteStore(store_path))
    await server.start()
    cluster = ClusterNode(server.broker, "127.0.0.1", 0, seeds,
                          heartbeat_interval_s=0.1,
                          failure_timeout_s=failure_timeout_s)
    await cluster.start()
    return Node(server, cluster)


async def start_cluster(tmp_path, n=3, failure_timeout_s=0.8):
    """n nodes sharing one store file (the Cassandra-analogue shared store)."""
    store = str(tmp_path / "shared.db")
    first = await start_node(store, [], failure_timeout_s)
    nodes = [first]
    for _ in range(n - 1):
        nodes.append(await start_node(store, [first.name], failure_timeout_s))
    # wait for full membership convergence on every node
    for _ in range(100):
        if all(len(node.cluster.membership.alive_members()) == n for node in nodes):
            break
        await asyncio.sleep(0.05)
    assert all(len(node.cluster.membership.alive_members()) == n for node in nodes)
    return nodes


def owner_and_other(nodes, vhost, queue_name):
    owner_name = nodes[0].cluster.queue_owner(vhost, queue_name)
    owner = next(node for node in nodes if node.name == owner_name)
    other = next(node for node in nodes if node.name != owner_name)
    return owner, other


async def test_queue_ops_location_transparent(tmp_path):
    nodes = await start_cluster(tmp_path, 3)
    try:
        owner, other = owner_and_other(nodes, "/", "cq")
        # declare via a NON-owner node: proxied to the owner
        c = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await c.channel()
        ok = await ch.queue_declare("cq", durable=True)
        assert ok.queue == "cq"
        # the owner actually holds it
        assert "cq" in owner.server.broker.vhosts["/"].queues
        assert "cq" not in other.server.broker.vhosts["/"].queues

        # publish via yet another non-owner: routed + pushed over RPC
        ch.basic_publish(b"m1", routing_key="cq", properties=PERSISTENT)
        await asyncio.sleep(0.3)
        ok = await ch.queue_declare("cq", passive=True)
        assert ok.message_count == 1

        # basic.get through the non-owner fetches from the owner
        msg = await ch.basic_get("cq")
        assert msg.body == b"m1"
        ch.basic_ack(msg.delivery_tag)
        await asyncio.sleep(0.2)
        assert (await ch.queue_declare("cq", passive=True)).message_count == 0

        # purge + delete through the non-owner
        ch.basic_publish(b"m2", routing_key="cq")
        await asyncio.sleep(0.2)
        assert await ch.queue_purge("cq") == 1
        assert await ch.queue_delete("cq") == 0
        await asyncio.sleep(0.2)
        assert ("/," "cq") not in owner.cluster.queue_metas
        await c.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_remote_consume_streams_deliveries(tmp_path):
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "stream_q")
        # consumer connects to the NON-owner node
        consumer_client = await AMQPClient.connect("127.0.0.1", other.port)
        cch = await consumer_client.channel()
        await cch.queue_declare("stream_q")
        got = []
        done = asyncio.get_event_loop().create_future()

        def on_msg(msg):
            got.append(msg)
            cch.basic_ack(msg.delivery_tag)
            if len(got) == 20 and not done.done():
                done.set_result(None)

        await cch.basic_consume("stream_q", on_msg)

        # producer connects to the OWNER node
        producer_client = await AMQPClient.connect("127.0.0.1", owner.port)
        pch = await producer_client.channel()
        for i in range(20):
            pch.basic_publish(f"s{i}".encode(), routing_key="stream_q")
        await asyncio.wait_for(done, 10)
        assert [m.body for m in got] == [f"s{i}".encode() for i in range(20)]
        # acks settled back to the owner: nothing outstanding
        await asyncio.sleep(0.3)
        queue = owner.server.broker.vhosts["/"].queues["stream_q"]
        assert len(queue.outstanding) == 0
        await producer_client.close()
        await consumer_client.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_exchange_metadata_replicated(tmp_path):
    nodes = await start_cluster(tmp_path, 3)
    try:
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.exchange_declare("reps", "topic", durable=True)
        await ch0.queue_declare("rep_q", durable=True)
        await ch0.queue_bind("rep_q", "reps", "a.#")
        await asyncio.sleep(0.3)
        # every node sees the exchange and the binding in its local matcher
        for node in nodes:
            vhost = node.server.broker.vhosts["/"]
            assert "reps" in vhost.exchanges
            assert vhost.exchanges["reps"].route("a.b") == {"rep_q"}
        # publish from the last node routes through its local matcher
        c2 = await AMQPClient.connect("127.0.0.1", nodes[2].port)
        ch2 = await c2.channel()
        ch2.basic_publish(b"routed", exchange="reps", routing_key="a.b.c",
                          properties=PERSISTENT)
        await asyncio.sleep(0.3)
        ok = await ch2.queue_declare("rep_q", passive=True)
        assert ok.message_count == 1
        await c0.close()
        await c2.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_failover_durable_messages_survive_node_death(tmp_path):
    """The HA contract: kill the owner under load; durable+persistent
    messages recover from the shared store on the new owner."""
    nodes = await start_cluster(tmp_path, 3)
    survivors = []
    try:
        owner, other = owner_and_other(nodes, "/", "ha_q")
        survivors = [n for n in nodes if n is not owner]
        c = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await c.channel()
        await ch.queue_declare("ha_q", durable=True)
        for i in range(10):
            ch.basic_publish(f"ha{i}".encode(), routing_key="ha_q",
                             properties=PERSISTENT)
        await asyncio.sleep(0.5)
        assert (await ch.queue_declare("ha_q", passive=True)).message_count == 10

        # kill the owner node (no clean shutdown of its queues)
        await owner.stop()
        # wait for the survivors to mark it down
        for _ in range(100):
            if all(owner.name not in s.cluster.membership.alive_members()
                   for s in survivors):
                break
            await asyncio.sleep(0.05)

        # the queue re-activates on its new owner from the shared store
        for _ in range(50):
            try:
                ok = await ch.queue_declare("ha_q", passive=True)
                if ok.message_count == 10:
                    break
            except Exception:
                ch = await c.channel()
            await asyncio.sleep(0.1)
        ok = await ch.queue_declare("ha_q", passive=True)
        assert ok.message_count == 10
        bodies = []
        for _ in range(10):
            msg = await ch.basic_get("ha_q", no_ack=True)
            bodies.append(msg.body)
        assert bodies == [f"ha{i}".encode() for i in range(10)]
        await c.close()
    finally:
        for node in survivors:
            await node.stop()


async def test_consumer_reregisters_after_owner_death(tmp_path):
    """A consumer attached via a surviving node keeps consuming after the
    queue's owner dies: the origin re-registers it with the new owner."""
    nodes = await start_cluster(tmp_path, 3)
    survivors = []
    try:
        owner, other = owner_and_other(nodes, "/", "resub_q")
        survivors = [n for n in nodes if n is not owner]
        c = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await c.channel()
        await ch.queue_declare("resub_q", durable=True)
        got = []

        def on_msg(msg):
            got.append(msg)
            ch.basic_ack(msg.delivery_tag)

        await ch.basic_consume("resub_q", on_msg)
        ch.basic_publish(b"before", routing_key="resub_q", properties=PERSISTENT)
        for _ in range(50):
            if got:
                break
            await asyncio.sleep(0.1)
        assert [m.body for m in got] == [b"before"]

        await owner.stop()
        for _ in range(100):
            if all(owner.name not in s.cluster.membership.alive_members()
                   for s in survivors):
                break
            await asyncio.sleep(0.05)
        # give re-registration a moment, then publish again via the origin
        await asyncio.sleep(1.0)
        ch.basic_publish(b"after", routing_key="resub_q", properties=PERSISTENT)
        for _ in range(100):
            if len(got) == 2:
                break
            await asyncio.sleep(0.1)
        assert [m.body for m in got] == [b"before", b"after"]
        await c.close()
    finally:
        for node in survivors:
            await node.stop()


async def test_cluster_worker_ids_unique(tmp_path):
    nodes = await start_cluster(tmp_path, 3)
    try:
        ids = {node.server.broker.idgen.worker_id for node in nodes}
        assert len(ids) == 3  # every node leased a distinct worker id
    finally:
        for node in nodes:
            await node.stop()


async def test_join_churn_no_loss_no_duplication(tmp_path):
    """A node JOINING under live durable traffic (ring reshuffle with no
    death): every published message is delivered exactly once and the
    consumer keeps consuming. The holder discipline makes this true — the
    serving node stays the routing target through the reshuffle instead of
    the new ring owner activating a second copy from the shared store
    (SURVEY.md §3.6 shard-rebalancing analogue).

    The failure timeout is raised to 3s for this test: node startup on a
    loaded single-core host can stall heartbeats past a 0.8s timeout,
    tripping the (by-design) spurious-failure path — this test is about
    the no-death reshuffle, the failover tests own the death path."""
    nodes = await start_cluster(tmp_path, 2, failure_timeout_s=3.0)
    joined = None
    try:
        c_prod = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        pch = await c_prod.channel()
        await pch.confirm_select()
        await pch.queue_declare("churn_q", durable=True)

        c_cons = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        cch = await c_cons.channel()
        got = []

        def on_msg(msg):
            got.append(bytes(msg.body))
            cch.basic_ack(msg.delivery_tag)

        await cch.basic_consume("churn_q", on_msg)

        total = 60
        published = 0

        async def publish_half(n):
            nonlocal published
            for _ in range(n):
                pch.basic_publish(b"c%03d" % published, routing_key="churn_q",
                                  properties=PERSISTENT)
                published += 1
                await asyncio.sleep(0.01)
            await pch.wait_unconfirmed_below(1, timeout=10)

        # spread of idle queues to evidence the reshuffle below (the
        # joiner takes ~1/3 of ring keys, so some of these must move)
        for i in range(16):
            await pch.queue_declare(f"spread_{i}", durable=True)
        serving_before = nodes[0].cluster.queue_owner("/", "churn_q")
        ring_before = {
            f"spread_{i}": nodes[0].cluster.ring.owner_entity(
                "q", "/", f"spread_{i}")
            for i in range(16)
        }

        # first half of the traffic on the 2-node ring
        await publish_half(total // 3)

        # a third node joins mid-traffic: ring reshuffles with no death
        store = str(tmp_path / "shared.db")
        join_task = asyncio.get_event_loop().create_task(
            start_node(store, [nodes[0].name], 3.0))
        await publish_half(total // 3)
        joined = await join_task
        # wait for 3-way membership convergence
        for _ in range(100):
            if all(len(n.cluster.membership.alive_members()) == 3
                   for n in (*nodes, joined)):
                break
            await asyncio.sleep(0.05)
        assert len(joined.cluster.membership.alive_members()) == 3

        # the ring really reshuffled (some idle queues moved to new owners)
        moved = [
            name for name, owner in ring_before.items()
            if nodes[0].cluster.ring.owner_entity("q", "/", name) != owner
        ]
        assert moved, "join did not reshuffle the ring — test is vacuous"
        # ...but the live traffic queue stays pinned to its serving node:
        # every node (including the joiner) routes churn_q to the holder
        await asyncio.sleep(0.3)  # let holder metas replicate to the joiner
        for node in (*nodes, joined):
            assert node.cluster.queue_owner("/", "churn_q") == serving_before

        # remaining traffic on the reshuffled ring
        await publish_half(total - published)

        for _ in range(200):
            if len(got) >= total:
                break
            await asyncio.sleep(0.05)
        expect = [b"c%03d" % i for i in range(total)]
        assert sorted(got) == expect, (
            f"lost={set(expect) - set(got)} dup={len(got) - len(set(got))}")
        assert got == expect  # FIFO order preserved across the join

        # and the queue is fully drained everywhere: no second copy holds
        # residual messages on any node
        await asyncio.sleep(0.3)
        for node in (*nodes, joined):
            vq = node.server.broker.vhosts["/"].queues.get("churn_q")
            if vq is not None:
                assert len(vq.messages) == 0 and len(vq.outstanding) == 0
        await c_prod.close()
        await c_cons.close()
    finally:
        for node in nodes:
            await node.stop()
        if joined is not None:
            await joined.stop()


async def test_pipelined_remote_publish_order_and_confirms(tmp_path):
    """Plain clustered publishes pipeline through one queue.push_many RPC
    per owner per read batch (broker.py _publish_clustered pending path):
    a burst published via a NON-owner must arrive complete and in order on
    the owner, publisher confirms must release only after the owner
    accepted the batch, and a mandatory publish mid-burst must drain the
    buffered pipeline first so per-queue FIFO holds."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "pipe_q")
        c = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await c.channel()
        await ch.confirm_select()
        await ch.queue_declare("pipe_q", durable=True)
        n = 400
        for i in range(n):
            if i == 200:
                # mandatory publish forces an inline remote push: the
                # buffered 0..199 must be drained before it goes out
                ch.basic_publish(b"m-%03d" % i, routing_key="pipe_q",
                                 properties=PERSISTENT, mandatory=True)
            else:
                ch.basic_publish(b"m-%03d" % i, routing_key="pipe_q",
                                 properties=PERSISTENT)
        await ch.wait_unconfirmed_below(1, timeout=60)
        q = owner.server.broker.vhosts["/"].queues["pipe_q"]
        assert len(q.messages) == n
        assert [qm.message.body for qm in q.messages] == \
            [b"m-%03d" % i for i in range(n)]

        # consume from the owner side: everything flows back out in order
        c2 = await AMQPClient.connect("127.0.0.1", owner.port)
        ch2 = await c2.channel()
        got, done = [], asyncio.get_event_loop().create_future()

        def cb(m):
            got.append(m.body)
            ch2.basic_ack(m.delivery_tag)
            if len(got) >= n and not done.done():
                done.set_result(None)

        await ch2.basic_consume("pipe_q", cb)
        await asyncio.wait_for(done, 30)
        assert got == [b"m-%03d" % i for i in range(n)]
        await c2.close()
        await c.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_remote_ack_then_cancel_not_inverted(tmp_path):
    """Settle coalescing (cluster/node.py settle_bg) must never let a
    cancel overtake an ack buffered in the same read batch: the owner
    would requeue the just-acked delivery and redeliver it. The drain-
    before-RPC rule in ClusterNode._call pins the order."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        owner, other = owner_and_other(nodes, "/", "ac_q")
        c = await AMQPClient.connect("127.0.0.1", other.port)
        ch = await c.channel()
        await ch.queue_declare("ac_q")
        cp = await AMQPClient.connect("127.0.0.1", owner.port)
        chp = await cp.channel()
        await chp.confirm_select()

        got, first = [], asyncio.get_event_loop().create_future()

        def cb(m):
            got.append(m)
            if not first.done():
                first.set_result(None)

        await ch.basic_consume("ac_q", cb)
        chp.basic_publish(b"only", routing_key="ac_q")
        await chp.wait_unconfirmed_below(1)
        await asyncio.wait_for(first, 15)
        ch.basic_ack(got[0].delivery_tag)
        await ch.basic_cancel(got[0].consumer_tag)
        await asyncio.sleep(0.5)
        q = owner.server.broker.vhosts["/"].queues["ac_q"]
        assert not q.outstanding
        assert len(q.messages) == 0
        assert await ch.basic_get("ac_q", no_ack=True) is None
        await c.close()
        await cp.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_two_process_cluster_end_to_end(tmp_path):
    """The full multi-host shape, no in-process shortcuts: two REAL broker
    processes booted from config (run_node: AMQP listener + cluster layer),
    gossiping over real sockets, sharing one store. A client on node A
    publishes into a queue owned by whichever node the ring picks; a client
    on the OTHER node consumes it all back. Validates the config-driven
    cluster wiring (server.from_config + ClusterNode seeds) that the
    in-process tests bypass."""
    import json as jsonlib
    import socket
    import subprocess
    import sys

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    store = str(tmp_path / "shared.db")
    a_amqp, a_cluster = free_port(), free_port()
    b_amqp, b_cluster = free_port(), free_port()

    a_admin, b_admin = free_port(), free_port()

    def node_cfg(amqp_port, cluster_port, admin_port, seeds):
        return {
            "chana.mq.amqp.interface": "127.0.0.1",
            "chana.mq.amqp.port": amqp_port,
            "chana.mq.admin.enabled": True,
            "chana.mq.admin.interface": "127.0.0.1",
            "chana.mq.admin.port": admin_port,
            "chana.mq.store.path": store,
            "chana.mq.cluster.enabled": True,
            "chana.mq.cluster.host": "127.0.0.1",
            "chana.mq.cluster.port": cluster_port,
            "chana.mq.cluster.seeds": seeds,
            "chana.mq.cluster.heartbeat-interval": "200ms",
            "chana.mq.cluster.failure-timeout": "5s",
        }

    async def admin_cluster(port):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(b"GET /admin/cluster HTTP/1.1\r\nHost: x\r\n\r\n")
        # the admin server closes after responding: read to EOF
        raw = await asyncio.wait_for(reader.read(-1), 5)
        writer.close()
        return jsonlib.loads(raw.partition(b"\r\n\r\n")[2])

    procs = []
    logs = []
    try:
        for amqp_port, cluster_port, admin_port, seeds in (
                (a_amqp, a_cluster, a_admin, []),
                (b_amqp, b_cluster, b_admin, [f"127.0.0.1:{a_cluster}"])):
            cfg_path = tmp_path / f"node{amqp_port}.json"
            cfg_path.write_text(jsonlib.dumps(
                node_cfg(amqp_port, cluster_port, admin_port, seeds)))
            log_file = open(tmp_path / f"node{amqp_port}.log", "w")
            logs.append(log_file)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "chanamq_tpu.broker.server",
                 "--config", str(cfg_path), "--log-level", "WARNING"],
                stdout=log_file, stderr=subprocess.STDOUT))

        def check_alive():
            from pathlib import Path

            for proc, log_file in zip(procs, logs):
                if proc.poll() is not None:
                    log_file.flush()
                    tail = Path(log_file.name).read_text()[-1500:]
                    raise RuntimeError(
                        f"node died rc={proc.returncode}: {tail}")

        # converge: both processes report 2 alive members over admin HTTP
        for _ in range(150):
            check_alive()
            try:
                va = await admin_cluster(a_admin)
                vb = await admin_cluster(b_admin)
                if (va.get("enabled") and vb.get("enabled")
                        and len(va["alive"]) == 2 and len(vb["alive"]) == 2):
                    break
            except (OSError, ValueError, asyncio.TimeoutError):
                pass
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("2-process membership never converged")

        ca = await AMQPClient.connect("127.0.0.1", a_amqp)
        cha = await ca.channel()
        await cha.confirm_select()
        await cha.queue_declare("xp_q", durable=True)
        # queue metadata replicates asynchronously: wait until BOTH nodes
        # know the queue before the second client touches it
        for _ in range(100):
            va = await admin_cluster(a_admin)
            vb = await admin_cluster(b_admin)
            if va.get("known_queues") and vb.get("known_queues"):
                break
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("queue metadata never replicated to B")
        cb = await AMQPClient.connect("127.0.0.1", b_amqp)
        chb = await cb.channel()
        await chb.queue_declare("xp_q", durable=True)

        n = 200
        for i in range(n):
            cha.basic_publish(b"xp-%03d" % i, routing_key="xp_q",
                              properties=PERSISTENT)
        await cha.wait_unconfirmed_below(1, timeout=60)

        got, done = [], asyncio.get_event_loop().create_future()

        def cb_msg(m):
            got.append(m.body)
            chb.basic_ack(m.delivery_tag)
            if len(got) >= n and not done.done():
                done.set_result(None)

        await chb.basic_consume("xp_q", cb_msg)
        await asyncio.wait_for(done, 60)
        assert got == [b"xp-%03d" % i for i in range(n)]
        await ca.close()
        await cb.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log_file in logs:
            log_file.close()


async def test_origin_death_requeues_outstanding(tmp_path):
    """A remote consumer's ORIGIN node dies with deliveries unacked: the
    owner's membership down-event must requeue them
    (ClusterNode._drop_origin_consumers) so a consumer elsewhere gets
    every message — nothing stays stuck outstanding."""
    nodes = await start_cluster(tmp_path, 3)
    try:
        owner, _ = owner_and_other(nodes, "/", "org_q")
        origin = next(n for n in nodes if n.name != owner.name)
        third = next(n for n in nodes
                     if n.name not in (owner.name, origin.name))

        c_prod = await AMQPClient.connect("127.0.0.1", owner.port)
        chp = await c_prod.channel()
        await chp.confirm_select()
        await chp.queue_declare("org_q", durable=True)
        c_cons = await AMQPClient.connect("127.0.0.1", origin.port)
        chc = await c_cons.channel()
        got = []
        await chc.basic_consume("org_q", lambda m: got.append(m))  # no acks
        for i in range(12):
            chp.basic_publish(b"o-%02d" % i, routing_key="org_q",
                              properties=PERSISTENT)
        await chp.wait_unconfirmed_below(1)
        for _ in range(100):
            if len(got) >= 12:
                break
            await asyncio.sleep(0.05)
        assert len(got) == 12  # all delivered to the doomed origin, unacked

        await origin.stop()  # origin dies with everything outstanding
        q = owner.server.broker.vhosts["/"].queues["org_q"]
        for _ in range(200):
            if not q.outstanding and len(q.messages) == 12:
                break
            await asyncio.sleep(0.05)
        assert not q.outstanding
        assert len(q.messages) == 12  # requeued, redelivery-ready

        c2 = await AMQPClient.connect("127.0.0.1", third.port)
        ch2 = await c2.channel()
        got2, done = [], asyncio.get_event_loop().create_future()

        def cb(m):
            got2.append(m.body)
            ch2.basic_ack(m.delivery_tag)
            if len(got2) >= 12 and not done.done():
                done.set_result(None)

        await ch2.basic_consume("org_q", cb)
        await asyncio.wait_for(done, 30)
        assert sorted(got2) == [b"o-%02d" % i for i in range(12)]
        await c_prod.close()
        await c2.close()
    finally:
        for node in nodes:
            try:
                await node.stop()
            except Exception:
                pass


async def test_double_failover_zero_loss(tmp_path):
    """Kill the queue's owner TWICE in succession (each time re-resolving
    the new owner from the ring): every confirmed persistent message must
    survive both failovers via shared-store recovery and drain completely
    from the last survivor."""
    nodes = await start_cluster(tmp_path, 3)
    live = list(nodes)
    total = 0
    try:
        for wave in range(2):
            owner_name = live[0].cluster.queue_owner("/", "drill_q")
            owner = next(n for n in live if n.name == owner_name)
            survivor = next(n for n in live if n.name != owner_name)
            c = await AMQPClient.connect("127.0.0.1", survivor.port)
            ch = await c.channel()
            await ch.confirm_select()
            await ch.queue_declare("drill_q", durable=True)
            for i in range(50):
                ch.basic_publish(b"w%d-%02d" % (wave, i),
                                 routing_key="drill_q", properties=PERSISTENT)
            await ch.wait_unconfirmed_below(1)
            total += 50
            await c.close()
            await owner.stop()
            live.remove(owner)
            for _ in range(100):
                if all(owner_name not in n.cluster.membership.alive_members()
                       for n in live):
                    break
                await asyncio.sleep(0.05)
            c = await AMQPClient.connect("127.0.0.1", live[0].port)
            ch = await c.channel()
            ok = None
            for _ in range(100):
                try:
                    ok = await ch.queue_declare("drill_q", passive=True)
                    if ok.message_count == total:
                        break
                except Exception:
                    ch = await c.channel()
                await asyncio.sleep(0.1)
            assert ok is not None and ok.message_count == total
            await c.close()

        c = await AMQPClient.connect("127.0.0.1", live[0].port)
        ch = await c.channel()
        got = 0
        while True:
            m = await ch.basic_get("drill_q")
            if m is None:
                break
            ch.basic_ack(m.delivery_tag)
            got += 1
        assert got == total
        await c.close()
    finally:
        for node in live:
            try:
                await node.stop()
            except Exception:
                pass


async def test_exchange_to_exchange_binds_replicated(tmp_path):
    """e2e bindings replicate cluster-wide (exbind meta events + the join
    snapshot): a publish entering at any node routes through the full
    exchange graph, and unbind replicates too."""
    nodes = await start_cluster(tmp_path, 3)
    try:
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.exchange_declare("g_src", "direct", durable=True)
        await ch0.exchange_declare("g_dst", "fanout", durable=True)
        await ch0.queue_declare("g_q", durable=True)
        await ch0.exchange_bind("g_dst", "g_src", "k")
        await ch0.queue_bind("g_q", "g_dst", "")
        await asyncio.sleep(0.3)
        # every node's local routing sees the graph
        for node in nodes:
            vhost = node.server.broker.vhosts["/"]
            assert vhost.route("g_src", "k") == {"g_q"}, node.name
        # publish entering at node 2 flows through the replicated graph
        c2 = await AMQPClient.connect("127.0.0.1", nodes[2].port)
        ch2 = await c2.channel()
        ch2.basic_publish(b"graph", exchange="g_src", routing_key="k",
                          properties=PERSISTENT)
        await asyncio.sleep(0.3)
        ok = await ch2.queue_declare("g_q", passive=True)
        assert ok.message_count == 1
        # unbind replicates: post-unbind publishes route nowhere
        await ch0.exchange_unbind("g_dst", "g_src", "k")
        await asyncio.sleep(0.3)
        for node in nodes:
            vhost = node.server.broker.vhosts["/"]
            assert vhost.route("g_src", "k") == set(), node.name
        # a node joining AFTER the bind existed learns it from the snapshot
        await ch0.exchange_bind("g_dst", "g_src", "k2")
        await asyncio.sleep(0.3)
        joiner = await start_node(str(tmp_path / "shared.db"), [nodes[0].name])
        nodes.append(joiner)
        await asyncio.sleep(0.5)
        vhost = joiner.server.broker.vhosts["/"]
        assert vhost.route("g_src", "k2") == {"g_q"}
        await c0.close()
        await c2.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_remote_consumer_cancel_notify_on_queue_delete(tmp_path):
    """Owner-side queue death under a remote consumer propagates a
    consumer.cancelled event to the origin, which deregisters the stub and
    sends the client a Basic.Cancel."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        # find a queue name owned by node 1 so node 0 consumes remotely
        name = None
        for i in range(100):
            cand = f"rccn_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True)
        tag = await ch0.basic_consume(name, lambda m: None)
        await asyncio.sleep(0.2)
        # delete via the owner node directly
        c1 = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        ch1 = await c1.channel()
        await ch1.queue_delete(name)
        for _ in range(100):
            if ch0.cancelled_consumers:
                break
            await asyncio.sleep(0.02)
        assert ch0.cancelled_consumers == [tag]
        await c0.close()
        await c1.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_tx_commit_over_remotely_owned_queue(tmp_path):
    """tx.commit replays publishes into remotely-owned queues through the
    pipelined push path and CommitOk arrives only after the owner accepted
    them (strict barrier)."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        name = None
        for i in range(100):
            cand = f"txc_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True)
        await ch0.tx_select()
        for i in range(20):
            ch0.basic_publish(b"tx%02d" % i, routing_key=name,
                              properties=PERSISTENT)
        # buffered: owner sees nothing yet
        c1 = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        ch1 = await c1.channel()
        ok = await ch1.queue_declare(name, passive=True)
        assert ok.message_count == 0
        await ch0.tx_commit()
        ok = await ch1.queue_declare(name, passive=True)
        assert ok.message_count == 20
        # rollback path drops cleanly too
        ch0.basic_publish(b"never", routing_key=name, properties=PERSISTENT)
        await ch0.tx_rollback()
        ok = await ch1.queue_declare(name, passive=True)
        assert ok.message_count == 20
        got = await ch1.basic_get(name, no_ack=True)
        assert got is not None and got.body == b"tx00"
        await c0.close()
        await c1.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_remote_consumer_priority_honored_by_owner(tmp_path):
    """x-priority forwarded over the consume RPC: the owner's dispatch
    prefers the remote high-priority consumer over a local default one."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        name = None
        for i in range(100):
            cand = f"prio_rc_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        # origin-side high-priority consumer (remote to the owner)
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True)
        hi_got, lo_got = [], []
        await ch0.basic_consume(name, hi_got.append, no_ack=True,
                                arguments={"x-priority": 7})
        # owner-local default-priority consumer
        c1 = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        ch1 = await c1.channel()
        await ch1.basic_consume(name, lo_got.append, no_ack=True)
        await asyncio.sleep(0.2)
        for i in range(8):
            ch1.basic_publish(b"p%d" % i, routing_key=name,
                              properties=PERSISTENT)
        await asyncio.sleep(0.5)
        # the remote high-priority consumer (credit window >> 8) gets all
        assert len(hi_got) == 8, (len(hi_got), len(lo_got))
        assert lo_got == []
        await c0.close()
        await c1.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_alternate_exchange_to_default_reaches_remote_queue(tmp_path):
    """AE "" fallback must see clustered queues that exist on the publishing
    node only as replicated metadata (the default-exchange implicit binding
    consults cluster.queue_metas, not just local queues)."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        name = None
        for i in range(100):
            cand = f"ae_remote_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True)
        await ch0.exchange_declare("ae_cluster_ex", "direct", arguments={
            "alternate-exchange": ""})
        await asyncio.sleep(0.2)
        # unroutable on the exchange; the AE "" must route by queue name to
        # the node-1-owned queue
        ch0.basic_publish(b"fell-to-remote", exchange="ae_cluster_ex",
                          routing_key=name, properties=PERSISTENT)
        await asyncio.sleep(0.4)
        c1 = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        ch1 = await c1.channel()
        ok = await ch1.queue_declare(name, passive=True)
        assert ok.message_count == 1
        got = await ch1.basic_get(name, no_ack=True)
        assert got is not None and got.body == b"fell-to-remote"
        await c0.close()
        await c1.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_priority_queue_ordering_on_remote_owner(tmp_path):
    """x-max-priority replicates with the queue metadata: publishes routed
    to a remote owner are ordered by priority there, and a consumer on the
    origin node receives them highest-first."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        name = None
        for i in range(100):
            cand = f"pr_rc_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True,
                                arguments={"x-max-priority": 9})
        await asyncio.sleep(0.2)
        for body, p in ((b"low-a", 1), (b"high-a", 9), (b"low-b", 1),
                        (b"high-b", 9)):
            ch0.basic_publish(body, routing_key=name, properties=BasicProperties(
                priority=p, delivery_mode=2))
        # ordering barrier via the owner
        c1 = await AMQPClient.connect("127.0.0.1", nodes[1].port)
        ch1 = await c1.channel()
        for _ in range(100):
            ok = await ch1.queue_declare(name, passive=True)
            if ok.message_count == 4:
                break
            await asyncio.sleep(0.02)
        assert ok.message_count == 4
        got = []
        done = asyncio.get_event_loop().create_future()

        def cb(m):
            got.append(m.body)
            if len(got) == 4 and not done.done():
                done.set_result(None)

        await ch0.basic_consume(name, cb, no_ack=True)
        await asyncio.wait_for(done, 10)
        assert got == [b"high-a", b"high-b", b"low-a", b"low-b"]
        await c0.close()
        await c1.close()
    finally:
        for node in nodes:
            await node.stop()


async def test_ack_timeout_fires_for_remote_consumers(tmp_path):
    """The ack-timeout sweep walks channel unacked maps, so a stuck
    consumer of a REMOTELY-owned queue is timed out by its origin node
    like any local consumer."""
    nodes = await start_cluster(tmp_path, 2)
    try:
        for node in nodes:
            node.server.broker.consumer_timeout_ms = 400
        name = None
        for i in range(100):
            cand = f"at_rc_q{i}"
            if nodes[0].cluster.queue_owner("/", cand) == nodes[1].name:
                name = cand
                break
        assert name is not None
        c0 = await AMQPClient.connect("127.0.0.1", nodes[0].port)
        ch0 = await c0.channel()
        await ch0.queue_declare(name, durable=True)
        got = []
        await ch0.basic_consume(name, got.append)  # never acks
        ch0.basic_publish(b"stuck-remote", routing_key=name,
                          properties=PERSISTENT)
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.02)
        assert got, "remote delivery never arrived"
        # origin sweep (1s default interval) times the channel out
        from chanamq_tpu.client.client import ChannelClosedError

        err = None
        for _ in range(120):
            try:
                await ch0.queue_declare(name, passive=True)
            except ChannelClosedError as exc:
                err = exc
                break
            await asyncio.sleep(0.05)
        assert err is not None and err.reply_code == 406, err
        await c0.close()
    finally:
        for node in nodes:
            await node.stop()
