// Native hot paths for chanamq_tpu: AMQP frame scanning and topic-trie
// routing.
//
// SURVEY.md §7.1 names the two measured hot paths worth a compiled
// implementation: (a) the frame parse loop (the reference's
// FrameParser.scala byte handling) and (b) the topic matcher (the
// reference's lock-free TrieMatcher, QueueMatcher.scala:140-601). Both are
// exposed through a minimal C ABI consumed via ctypes — no pybind11 in this
// image. The Python implementations remain as behavioral reference and
// fallback.
//
// Build: make -C native   ->  native/libchanamq_native.so

#include <cstdint>
#include <cstring>
#include <set>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// frame scanning
// ---------------------------------------------------------------------------

// Scan `buf` for complete AMQP frames (type u8 | channel u16be | size u32be |
// payload | 0xCE). Writes up to max_frames entries into the parallel output
// arrays. Returns the number of frames found.
//   *consumed  <- bytes covered by complete frames (caller trims its buffer)
//   *error     <- 0 ok; 1 unknown frame type; 2 frame exceeds frame_max;
//                 3 missing end octet
// On error, frames found before the error are still reported.
int chana_scan_frames(const uint8_t* buf, int64_t len, uint32_t frame_max,
                      int32_t* types, int32_t* channels, int64_t* offsets,
                      int64_t* lengths, int32_t max_frames, int64_t* consumed,
                      int32_t* error) {
  int n = 0;
  int64_t pos = 0;
  *error = 0;
  while (len - pos >= 7 && n < max_frames) {
    uint8_t type = buf[pos];
    if (type != 1 && type != 2 && type != 3 && type != 8) {
      *error = 1;
      break;
    }
    uint32_t channel = (uint32_t(buf[pos + 1]) << 8) | buf[pos + 2];
    uint32_t size = (uint32_t(buf[pos + 3]) << 24) |
                    (uint32_t(buf[pos + 4]) << 16) |
                    (uint32_t(buf[pos + 5]) << 8) | buf[pos + 6];
    if (frame_max != 0 && uint64_t(size) + 8 > frame_max) {
      *error = 2;
      break;
    }
    int64_t end = pos + 7 + int64_t(size);
    if (end + 1 > len) break;  // incomplete: wait for more bytes
    if (buf[end] != 0xCE) {
      *error = 3;
      break;
    }
    types[n] = type;
    channels[n] = int32_t(channel);
    offsets[n] = pos + 7;
    lengths[n] = int64_t(size);
    ++n;
    pos = end + 1;
  }
  *consumed = pos;
  return n;
}

// ---------------------------------------------------------------------------
// topic trie
// ---------------------------------------------------------------------------

namespace {

struct TrieNode {
  std::unordered_map<std::string, TrieNode*> children;
  std::set<int32_t> queues;

  ~TrieNode() {
    for (auto& kv : children) delete kv.second;
  }
};

struct Trie {
  TrieNode root;
  // (pattern, queue) registry for duplicate detection
  std::set<std::pair<std::string, int32_t>> bindings;
};

void split_words(const char* key, std::vector<std::string>* out) {
  const char* start = key;
  const char* p = key;
  for (;; ++p) {
    if (*p == '.' || *p == '\0') {
      out->emplace_back(start, p - start);
      if (*p == '\0') break;
      start = p + 1;
    }
  }
}

void walk(const TrieNode* node, const std::vector<std::string>& words,
          size_t i, std::unordered_set<int32_t>* out) {
  if (i == words.size()) {
    out->insert(node->queues.begin(), node->queues.end());
    // trailing '#' chains match zero remaining words
    const TrieNode* tail = node;
    for (;;) {
      auto it = tail->children.find("#");
      if (it == tail->children.end()) break;
      tail = it->second;
      out->insert(tail->queues.begin(), tail->queues.end());
    }
    return;
  }
  auto exact = node->children.find(words[i]);
  if (exact != node->children.end()) walk(exact->second, words, i + 1, out);
  auto star = node->children.find("*");
  if (star != node->children.end()) walk(star->second, words, i + 1, out);
  auto hash = node->children.find("#");
  if (hash != node->children.end()) {
    for (size_t j = i; j <= words.size(); ++j)
      walk(hash->second, words, j, out);
  }
}

}  // namespace

void* chana_trie_new() { return new Trie(); }

void chana_trie_free(void* handle) { delete static_cast<Trie*>(handle); }

// returns 1 when the binding was added, 0 when it already existed
int chana_trie_bind(void* handle, const char* pattern, int32_t queue_id) {
  Trie* trie = static_cast<Trie*>(handle);
  if (!trie->bindings.emplace(pattern, queue_id).second) return 0;
  std::vector<std::string> words;
  split_words(pattern, &words);
  TrieNode* node = &trie->root;
  for (const auto& word : words) {
    TrieNode*& child = node->children[word];
    if (child == nullptr) child = new TrieNode();
    node = child;
  }
  node->queues.insert(queue_id);
  return 1;
}

// returns 1 when the binding existed and was removed
int chana_trie_unbind(void* handle, const char* pattern, int32_t queue_id) {
  Trie* trie = static_cast<Trie*>(handle);
  if (trie->bindings.erase({pattern, queue_id}) == 0) return 0;
  std::vector<std::string> words;
  split_words(pattern, &words);
  // collect the path, then prune empty branches bottom-up (the reference's
  // tomb/contract step, QueueMatcher.scala:283-347)
  std::vector<std::pair<TrieNode*, std::string>> path;
  TrieNode* node = &trie->root;
  for (const auto& word : words) {
    auto it = node->children.find(word);
    if (it == node->children.end()) return 1;  // registry was authoritative
    path.emplace_back(node, word);
    node = it->second;
  }
  node->queues.erase(queue_id);
  for (auto it = path.rbegin(); it != path.rend(); ++it) {
    TrieNode* child = it->first->children[it->second];
    if (!child->queues.empty() || !child->children.empty()) break;
    it->first->children.erase(it->second);
    delete child;
  }
  return 1;
}

// routes `key`; writes up to max_out queue ids; returns the match count
int chana_trie_route(void* handle, const char* key, int32_t* out,
                     int32_t max_out) {
  Trie* trie = static_cast<Trie*>(handle);
  std::vector<std::string> words;
  split_words(key, &words);
  std::unordered_set<int32_t> matches;
  walk(&trie->root, words, 0, &matches);
  // Returns the TOTAL match count while writing at most max_out ids, so the
  // caller can detect truncation and retry with a larger buffer.
  int32_t n = 0;
  for (int32_t id : matches) {
    if (n < max_out) out[n] = id;
    n++;
  }
  return n;
}

int chana_trie_size(void* handle) {
  return int(static_cast<Trie*>(handle)->bindings.size());
}

}  // extern "C"
