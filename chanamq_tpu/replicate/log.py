"""Owner-side replication: per-queue mutation logs + the node manager.

The replication unit is the queue's DURABLE STORE STATE, not its wire
traffic: every store-mutation funnel in broker/entities.py appends one
sequenced event here (enqueue rides the queue-log row insert, settles ride
the unack-row deletes, watermark moves ride the persisted watermark), so a
follower that applies the stream in order holds exactly the rows the owner
would recover from its own store. Transient messages are never shipped —
they make no durability promise and die with the owner, same as the
single-node contract.

Ship path: events buffer per queue and a per-queue ship task drains them in
batches (bounded by chana.mq.replicate.batch-max events and a byte budget)
to every follower concurrently over the cluster RPC mesh. The owner keeps
NO shipped-event history — a follower that misses a batch detects the
sequence gap and resyncs wholesale from the owner's store (the snapshot
covers every event at or below its captured seq; later events re-apply
idempotently on top). Each batch piggybacks the full follower-ack map so
followers know their peers' sync state for deterministic promotion
election when the owner dies.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import deque
from typing import TYPE_CHECKING, Optional

from .. import chaos
from ..store.api import StoredQueue
from .applier import ReplicaApplier

if TYPE_CHECKING:  # pragma: no cover
    from ..broker.entities import Message, Queue, QueuedMessage
    from ..cluster.node import ClusterNode

log = logging.getLogger("chanamq.replicate")


class QueueRepLog:
    """One queue's outgoing replication log (owner side)."""

    __slots__ = ("vhost", "name", "manager", "seq", "pending",
                 "pending_bytes", "followers", "closed", "_ship_task",
                 "_ack_event")

    def __init__(self, vhost: str, name: str, manager: "ReplicationManager") -> None:
        self.vhost = vhost
        self.name = name
        self.manager = manager
        self.seq = 0                      # last assigned event sequence
        self.pending: deque[dict] = deque()
        self.pending_bytes = 0
        # follower node -> highest acked (applied) seq
        self.followers: dict[str, int] = {}
        self.closed = False
        self._ship_task: Optional[asyncio.Task] = None
        self._ack_event = asyncio.Event()

    # -- event append (called synchronously from entity hot paths) ---------

    def append(self, op: str, data: dict) -> None:
        if self.closed:
            return
        self.seq += 1
        data["s"] = self.seq
        data["op"] = op
        self.pending.append(data)
        self.pending_bytes += len(data.get("body") or b"")
        self.manager._ship_soon(self)

    def enqueue(self, qm: "QueuedMessage", message: "Message") -> None:
        """Ship one durable+persistent enqueue (body travels with the event;
        a fanout sibling may have already passivated the shared body — the
        follower then pulls the blob from the owner's store via resync)."""
        self.append("enqueue", {
            "o": qm.offset, "m": message.id, "z": qm.body_size,
            "e": qm.expire_at_ms, "body": message.body,
            "props": message.header_payload(), "ex": message.exchange,
            "rk": message.routing_key, "ttl": message.ttl_ms,
        })

    # -- sync state ---------------------------------------------------------

    def live_ack_floor(self) -> int:
        """Lowest acked seq among followers membership says are alive.
        With no live follower there is nobody to wait for: the floor is the
        head (sync barriers pass — durability then rests on the local
        store, exactly the pre-replication contract)."""
        membership = self.manager.node.membership
        floors = [
            acked for name, acked in self.followers.items()
            if membership is not None and membership.is_alive(name)
        ]
        return min(floors) if floors else self.seq

    def lag(self) -> int:
        return max(0, self.seq - self.live_ack_floor())


class ReplicationManager:
    """Per-node replication coordinator: owns every local queue's outgoing
    log, the follower-side applier, and the promotion protocol."""

    _SHIP_BYTES = 8 * 1024 * 1024   # early batch cut-off (body bytes)
    _ROWS_PAGE = 4096               # resync snapshot page size

    def __init__(
        self,
        node: "ClusterNode",
        *,
        factor: int = 2,
        sync: bool = False,
        batch_max: int = 256,
        ack_timeout_ms: int = 1000,
    ) -> None:
        self.node = node
        self.broker = node.broker
        self.factor = factor
        self.sync = sync
        self.batch_max = max(1, batch_max)
        self.ack_timeout_s = ack_timeout_ms / 1000.0
        self._logs: dict[tuple[str, str], QueueRepLog] = {}
        self._promoting: dict[tuple[str, str], asyncio.Future] = {}
        self.applier = ReplicaApplier(self)
        node.rpc.register("repl.append", self.applier.h_append)
        node.rpc.register("repl.resync", self._h_resync)
        node.rpc.register("repl.rows", self._h_rows)
        node.rpc.register("repl.fetch", self._h_fetch)
        node.rpc.register("repl.probe", self.applier.h_probe)
        node.rpc.register("repl.retire", self.applier.h_retire)

    @property
    def metrics(self):
        return self.broker.metrics

    def client_for(self, name: str):
        assert self.node.membership is not None
        return self.node.membership.client(name)

    # ------------------------------------------------------------------
    # attach / detach (queue lifecycle on the owner)
    # ------------------------------------------------------------------

    def _select_followers(self, vhost: str, name: str) -> list[str]:
        prefs = self.node.ring.preference_entity("q", vhost, name, self.factor)
        return [n for n in prefs if n != self.node.name][: self.factor - 1]

    def attach(self, queue: "Queue") -> None:
        """This node now serves `queue`: open (or re-bind) its replication
        log. Exclusive and transient queues never replicate — they make no
        cross-restart promise to mirror."""
        if queue.exclusive_owner is not None or not queue.durable:
            return
        key = (queue.vhost, queue.name)
        repl = self._logs.get(key)
        if repl is None:
            repl = QueueRepLog(queue.vhost, queue.name, self)
            for follower in self._select_followers(queue.vhost, queue.name):
                repl.followers[follower] = 0
            self._logs[key] = repl
        if getattr(queue, "repl", None) is not repl:
            queue.repl = repl
            self._meta_event(repl, queue)

    def _meta_event(self, repl: QueueRepLog, queue: "Queue") -> None:
        # backlog > 0 tells a fresh follower its copy is incomplete (the
        # queue existed before the log opened) so it resyncs from the store
        backlog = len(queue.messages) + len(queue.outstanding)
        repl.append("meta", {
            "durable": queue.durable, "ttl": queue.ttl_ms,
            "args": json.dumps(queue.arguments or {}),
            "wm": queue.last_consumed, "backlog": backlog,
        })

    def detach(self, vhost: str, name: str, *, deleted: bool = False) -> None:
        key = (vhost, name)
        repl = self._logs.get(key)
        if repl is None:
            return
        if deleted:
            repl.append("delete", {})
        repl.closed = True
        if not repl.pending:
            self._logs.pop(key, None)

    # ------------------------------------------------------------------
    # ship loop
    # ------------------------------------------------------------------

    def _ship_soon(self, repl: QueueRepLog) -> None:
        if repl._ship_task is None or repl._ship_task.done():
            repl._ship_task = asyncio.get_event_loop().create_task(
                self._ship(repl))

    async def _ship(self, repl: QueueRepLog) -> None:
        membership = self.node.membership
        while repl.pending:
            batch: list[dict] = []
            nbytes = 0
            while (repl.pending and len(batch) < self.batch_max
                   and nbytes < self._SHIP_BYTES):
                event = repl.pending.popleft()
                nbytes += len(event.get("body") or b"")
                batch.append(event)
            repl.pending_bytes -= nbytes
            targets = [
                n for n in repl.followers
                if membership is not None and membership.is_alive(n)
            ]
            if targets:
                payload = {
                    "vhost": repl.vhost, "queue": repl.name,
                    "owner": self.node.name, "base": batch[0]["s"],
                    "events": batch,
                    "acks": dict(repl.followers),
                    # fencing: followers refuse batches stamped with an
                    # epoch older than the holdership they know about
                    "epoch": self.node.queue_epoch(repl.vhost, repl.name),
                }
                await asyncio.gather(*(
                    self._ship_one(repl, follower, payload)
                    for follower in targets))
            self.metrics.repl_events_shipped += len(batch)
            self.metrics.repl_batches_shipped += 1
            repl._ack_event.set()
        if repl.closed:
            self._logs.pop((repl.vhost, repl.name), None)

    async def _ship_one(
        self, repl: QueueRepLog, follower: str, payload: dict
    ) -> None:
        t0 = time.perf_counter()
        try:
            if chaos.ACTIVE is not None:
                fault = await chaos.ACTIVE.fire(
                    "repl.ship", peer=follower,
                    on_error=lambda f: OSError(f"chaos[{f.rule}]: {f.message}"))
                if fault is not None:
                    # batch lost toward this follower: it gap-detects on the
                    # next one and resyncs wholesale (the designed path)
                    raise OSError(f"chaos[{fault.rule}]: batch dropped")
            reply = await self.client_for(follower).call(
                "repl.append", payload, timeout_s=self.ack_timeout_s)
            applied = int(reply.get("applied", 0))
            if applied > repl.followers.get(follower, 0):
                repl.followers[follower] = applied
            self.metrics.repl_ack_us.observe_us(
                (time.perf_counter() - t0) * 1e6)
        except (OSError, asyncio.TimeoutError) as exc:
            self.metrics.repl_ack_timeouts += 1
            log.debug("%s: repl.append to %s failed: %r",
                      self.node.name, follower, exc)
        except Exception as exc:  # noqa: BLE001 — RpcError / codec trouble
            self.metrics.repl_ack_timeouts += 1
            log.warning("%s: repl.append to %s failed: %r",
                        self.node.name, follower, exc)

    async def sync_barrier(self) -> None:
        """Block until every live follower of every local log has acked the
        log head, or the ack timeout passes (timeout: count it and proceed —
        a wedged follower must not wedge every publisher; it will gap-detect
        and resync)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.ack_timeout_s
        for repl in list(self._logs.values()):
            target = repl.seq
            while repl.live_ack_floor() < target:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    self.metrics.repl_ack_timeouts += 1
                    return
                repl._ack_event.clear()
                try:
                    await asyncio.wait_for(repl._ack_event.wait(), remaining)
                except asyncio.TimeoutError:
                    pass

    # ------------------------------------------------------------------
    # graceful handoff (drain / rebalance)
    # ------------------------------------------------------------------

    async def prepare_handoff(
        self, vhost: str, name: str, target: str,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Gate a graceful holdership move: make sure ``target`` holds a
        replica copy synced to this log's head before anything moves.
        Adds the target as a follower if the ring didn't already pick it
        (a join target, or the only node left standing), nudges it with a
        meta event (backlog > 0 makes a fresh follower resync wholesale
        from this node's store), then polls its applied seq up to the
        head. Nothing here is destructive — a timeout just refuses the
        handoff and the queue stays where it is."""
        from ..cluster.rpc import RpcError

        key = (vhost, name)
        vh = self.broker.vhosts.get(vhost)
        queue = vh.queues.get(name) if vh is not None else None
        if queue is None:
            return False
        repl = self._logs.get(key)
        if repl is None:
            # a previous aborted handoff may have closed the log: reattach
            self.attach(queue)
            repl = self._logs.get(key)
            if repl is None:
                return False
        queue.flush_store_buffers()
        if target not in repl.followers:
            repl.followers[target] = 0
        self._meta_event(repl, queue)
        loop = asyncio.get_event_loop()
        deadline = loop.time() + (
            timeout_s if timeout_s is not None
            else max(5.0, self.ack_timeout_s * 5))
        while repl.followers.get(target, 0) < repl.seq:
            if loop.time() >= deadline:
                log.warning(
                    "%s: handoff prepare of %s/%s -> %s timed out "
                    "(acked %d < head %d)", self.node.name, vhost, name,
                    target, repl.followers.get(target, 0), repl.seq)
                return False
            await asyncio.sleep(0.03)
            try:
                reply = await self.client_for(target).call(
                    "repl.probe",
                    {"vhost": vhost, "queue": name,
                     "owner": self.node.name},
                    timeout_s=self.ack_timeout_s)
                applied = int(reply.get("applied", -1))
                if applied > repl.followers.get(target, 0):
                    repl.followers[target] = applied
            except (RpcError, OSError, asyncio.TimeoutError):
                pass  # transient; the deadline bounds us
        return True

    async def materialize_copy(self, vhost: str, name: str) -> bool:
        """Graceful-handoff twin of the death promotion: turn this node's
        replica copy into the live queue. No election — the source
        coordinated the move and synced our copy to its head first. No-op
        without a copy (shared-store deployments activate from the store
        instead)."""
        key = (vhost, name)
        fut = self._promoting.get(key)
        if fut is not None:
            await fut
            return True
        copy = self.applier.copies.get(key)
        if copy is None:
            return False
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._promoting[key] = fut
        await self._promote(key, copy, fut, reason="handoff")
        return True

    # ------------------------------------------------------------------
    # membership reactions + promotion
    # ------------------------------------------------------------------

    def on_membership(self) -> None:
        """Recompute follower sets from the (already updated) ring. Retained
        followers keep their ack state; new ones start at 0 and resync on
        the first batch they see (gap or meta-backlog detection). Dropped
        followers are told to discard their copies: a copy that will never
        see another ship is not a safety net but a split-election seed —
        were the owner to die later, the dropped follower and the current
        one would each elect themselves from disjoint ack maps. Best-effort
        (a partitioned ex-follower keeps its copy; the dual-holder
        reconcile mops up that corner)."""
        membership = self.node.membership
        for repl in self._logs.values():
            wanted = self._select_followers(repl.vhost, repl.name)
            fresh = [n for n in wanted if n not in repl.followers]
            dropped = [n for n in repl.followers if n not in wanted]
            repl.followers = {n: repl.followers.get(n, 0) for n in wanted}
            for name in dropped:
                if membership is None or not membership.is_alive(name):
                    continue
                asyncio.get_event_loop().create_task(
                    self._retire_one(name, repl.vhost, repl.name))
            if fresh:
                vh = self.broker.vhosts.get(repl.vhost)
                queue = vh.queues.get(repl.name) if vh is not None else None
                if queue is not None:
                    # a meta event wakes the new follower; backlog > 0 makes
                    # it pull the full snapshot
                    self._meta_event(repl, queue)
            if repl.pending:
                self._ship_soon(repl)

    async def _retire_one(self, follower: str, vhost: str, name: str) -> None:
        from ..cluster.rpc import RpcError

        try:
            await self.client_for(follower).call(
                "repl.retire",
                {"vhost": vhost, "queue": name, "owner": self.node.name},
                timeout_s=self.ack_timeout_s)
        except (RpcError, OSError, asyncio.TimeoutError):
            pass  # best-effort; the dual-holder reconcile covers the miss

    def on_node_down(self, dead: str) -> None:
        """Owner side: re-pick followers. Follower side: elect a promotion
        winner for every copy whose owner just died. The election is
        deterministic — highest (acked seq, node name) wins, judged from
        the dead owner's last piggybacked ack map (each node's own applied
        seq is authoritative for itself) — so at most one surviving
        follower promotes."""
        from ..cluster.membership import DRAINING, LEFT

        self.on_membership()
        me = self.node.name
        membership = self.node.membership

        def electable(name: str) -> bool:
            # draining/left nodes keep serving copies (they are handoff
            # sources) but must never WIN a failover election: a
            # decommissioned node re-claiming a queue would undo its own
            # evacuation. Every voter applies the same lifecycle filter,
            # so the election stays single-winner.
            if membership is None:
                return True
            return membership.lifecycle_of(name) not in (DRAINING, LEFT)

        for key, copy in list(self.applier.copies.items()):
            if copy.owner != dead or key in self._promoting:
                continue
            holder = (self.node.queue_metas.get(key) or {}).get("holder")
            if (holder and holder != dead and membership is not None
                    and membership.is_alive(holder)):
                # the queue already moved on (evacuated or promoted while
                # this copy idled): electing from the relic would steal
                # holdership back from the live owner with a fresher epoch
                continue
            contenders = {me: copy.applied_seq} if electable(me) else {}
            for name, acked in (copy.peer_acks or {}).items():
                if (name != me and name != dead and membership is not None
                        and membership.is_alive(name) and electable(name)):
                    contenders[name] = int(acked)
            if not contenders:
                continue
            winner = max(contenders.items(), key=lambda kv: (kv[1], kv[0]))[0]
            if winner != me:
                continue
            loop = asyncio.get_event_loop()
            fut: asyncio.Future = loop.create_future()
            self._promoting[key] = fut
            loop.create_task(self._promote(key, copy, fut))

    async def await_promotion(self, vhost: str, name: str) -> None:
        """Broker hook: activate_queue blocks on an in-flight promotion so a
        racing consumer-reconcile can't cold-activate an empty shell over
        the warm copy."""
        fut = self._promoting.get((vhost, name))
        if fut is not None:
            await fut

    async def _promote(
        self, key: tuple[str, str], copy, fut: asyncio.Future,
        *, reason: str = "failover",
    ) -> None:
        vhost_name, name = key
        try:
            rows = sorted(copy.rows.items())
            sq = StoredQueue(
                vhost=vhost_name, name=name, durable=True,
                ttl_ms=copy.ttl_ms, last_consumed=copy.wm,
                arguments=dict(copy.arguments),
                msgs=[(o, m, z, e) for o, (m, z, e) in rows],
                unacks={m: (o, z, e) for m, (o, z, e) in copy.unacks.items()},
            )
            store = self.broker.store
            await store.insert_queue_meta(sq)
            await store.replace_queue_msgs(vhost_name, name, list(sq.msgs))
            await store.replace_queue_unacks(
                vhost_name, name,
                [(m, o, z, e) for m, (o, z, e) in copy.unacks.items()])
            vhost = self.broker.vhosts.get(vhost_name)
            if vhost is None:
                await self.broker.create_vhost(vhost_name)
                vhost = self.broker.vhosts[vhost_name]
            queue = vhost.queues.get(name)
            if queue is None:
                queue = await self.broker._load_stored_queue(sq)
                vhost.queues[name] = queue
            self.node.claim_queue(queue)
            self.attach(queue)
            self.applier.release_copy(key)
            if reason == "failover":
                self.metrics.repl_promotions += 1
            log.info(
                "%s: promoted replica of %s/%s at seq %d (%s; "
                "%d ready, %d unacked requeued)",
                self.node.name, vhost_name, name, copy.applied_seq,
                reason, len(sq.msgs), len(sq.unacks))
        except Exception:
            log.exception("%s: promotion of %s/%s failed",
                          self.node.name, vhost_name, name)
        finally:
            self._promoting.pop(key, None)
            if not fut.done():
                fut.set_result(None)

    # ------------------------------------------------------------------
    # owner-side resync serving
    # ------------------------------------------------------------------

    async def _h_resync(self, payload: dict) -> dict:
        from ..cluster.rpc import RpcError

        vhost = str(payload["vhost"])
        name = str(payload["queue"])
        repl = self._logs.get((vhost, name))
        if repl is None:
            raise RpcError(
                "not_replicating", f"{vhost}/{name} has no log on this node")
        vh = self.broker.vhosts.get(vhost)
        queue = vh.queues.get(name) if vh is not None else None
        if queue is not None:
            # land per-tick coalescing buffers so the store snapshot is
            # current; the store queue is FIFO, so the reads below see them
            queue.flush_store_buffers()
        seq = repl.seq
        sq = await self.broker.store.select_queue(vhost, name)
        if sq is None:
            sq = StoredQueue(vhost=vhost, name=name)
            if queue is not None:
                sq.ttl_ms = queue.ttl_ms
                sq.arguments = dict(queue.arguments or {})
                sq.last_consumed = queue.last_consumed
        rows = sq.msgs
        return {
            "seq": seq, "durable": sq.durable, "ttl": sq.ttl_ms,
            "args": json.dumps(sq.arguments or {}), "wm": sq.last_consumed,
            "rows": [list(r) for r in rows[: self._ROWS_PAGE]],
            "more": len(rows) > self._ROWS_PAGE,
            "unacks": [[m, o, z, e] for m, (o, z, e) in sq.unacks.items()],
        }

    async def _h_rows(self, payload: dict) -> dict:
        rows = await self.broker.store.iter_queue_msgs(
            str(payload["vhost"]), str(payload["queue"]),
            int(payload.get("after", 0)), self._ROWS_PAGE)
        return {"rows": [list(r) for r in rows],
                "more": len(rows) >= self._ROWS_PAGE}

    async def _h_fetch(self, payload: dict) -> dict:
        ids = [int(i) for i in payload.get("ids") or []]
        msgs = await self.broker.store.select_messages(ids)
        return {"msgs": [
            [m.id, m.properties_raw, m.body, m.exchange, m.routing_key,
             m.ttl_ms]
            for m in msgs.values()
        ]}

    # ------------------------------------------------------------------
    # introspection (admin / metrics)
    # ------------------------------------------------------------------

    def total_lag(self) -> int:
        return sum(repl.lag() for repl in self._logs.values())

    def status(self) -> dict:
        queues: dict[str, dict] = {}
        for (vh, name), repl in self._logs.items():
            queues[f"{vh}/{name}"] = {
                "role": "owner", "seq": repl.seq,
                "followers": dict(repl.followers),
                "lag": repl.lag(), "pending": len(repl.pending),
            }
        for (vh, name), copy in self.applier.copies.items():
            queues.setdefault(f"{vh}/{name}", {
                "role": "follower", "owner": copy.owner,
                "applied_seq": copy.applied_seq,
                "messages": len(copy.rows), "unacked": len(copy.unacks),
                "resyncing": copy.resyncing,
            })
        return {
            "enabled": True, "factor": self.factor, "sync": self.sync,
            "batch_max": self.batch_max,
            "ack_timeout_ms": int(self.ack_timeout_s * 1000),
            "promoting": [f"{v}/{n}" for v, n in self._promoting],
            "queues": queues,
        }
