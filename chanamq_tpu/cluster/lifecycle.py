"""Node lifecycle: graceful drain / decommission coordination.

The proactive half of the cluster's elasticity contract (the reactive
half — death promotion — lives in replicate/log.py). A drain walks the
gossiped per-node state machine

    joining -> active -> draining -> left

(states defined in membership.py, versioned independently of liveness so
they converge through the same heartbeat piggyback). Entering DRAINING:

- flips ``broker.draining`` so readiness (/admin/health) reports the node
  as leaving and load balancers stop sending new clients,
- removes the node from every peer's placement ring (placement_members),
  so no NEW holdership hashes onto it while it keeps serving what it
  still holds,
- then evacuates every held queue, smallest name first, through the
  existing ``handoff_queue`` machinery with bounded retry/backoff. Each
  evacuation passes a per-queue CONFIRM BARRIER first: outstanding
  deliveries settle, coalesced store buffers land, the group commit
  flushes (releasing publisher confirms and stream-cursor commits), and
  the replication sync gate drains — only then does holdership move, so
  nothing a client saw confirmed can be lost mid-move.

When the last queue is gone the node gossips LEFT. Queues that cannot
move (stream queues pin their segment log to the node's private store;
queues with locally-attached AMQP consumers) are reported as ``pinned``
and keep the node in DRAINING — the ``drain-stuck`` alert fires once the
evacuation budget is exceeded.

Every evacuation lands in a canonical log (sorted keys, no wall-clock
fields) so two same-seed chaos runs compare byte-for-byte — the same
replayability contract as the control plane's decision log.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import TYPE_CHECKING, Optional

from .. import chaos, events
from .membership import DRAINING, LEFT

if TYPE_CHECKING:  # pragma: no cover
    from .node import ClusterNode

log = logging.getLogger("chanamq.lifecycle")


class LifecycleCoordinator:
    """Owns one node's drain state machine and evacuation loop."""

    def __init__(
        self,
        node: "ClusterNode",
        *,
        retry_limit: int = 5,
        backoff_ms: int = 100,
        backoff_cap_ms: int = 2000,
        budget_s: float = 30.0,
        settle_timeout_s: float = 5.0,
    ) -> None:
        self.node = node
        self.retry_limit = max(1, int(retry_limit))
        self.backoff_s = max(0.001, backoff_ms / 1000.0)
        self.backoff_cap_s = max(self.backoff_s, backoff_cap_ms / 1000.0)
        self.budget_s = float(budget_s)
        self.settle_timeout_s = float(settle_timeout_s)
        # idle -> draining -> drained | stuck
        self.state = "idle"
        self.queues_total = 0
        self.queues_moved = 0
        self.retries = 0
        self.failed: list[str] = []
        self.pinned: list[str] = []
        self.current: Optional[str] = None
        self.log_entries: list[dict] = []
        self._started_mono: Optional[float] = None
        self._task: Optional[asyncio.Task] = None
        self._done = asyncio.Event()

    def _set_state(self, state: str) -> None:
        """Transition the drain state machine, announcing the move on the
        event bus (``lifecycle.<state>``) when one is installed."""
        self.state = state
        bus = events.ACTIVE
        if bus is not None:
            bus.emit(f"lifecycle.{state}", {
                "node": self.node.name, "state": state,
                "queues_total": self.queues_total,
                "queues_moved": self.queues_moved,
                "retries": self.retries,
            })

    # ------------------------------------------------------------------
    # public surface (admin + soak)
    # ------------------------------------------------------------------

    def drain(self) -> dict:
        """Start (or observe — idempotent) the drain. Returns progress;
        the evacuation itself runs as a background task."""
        if self._task is None:
            self.node.broker.metrics.lifecycle_drains_started += 1
            self._set_state("draining")
            self._started_mono = time.monotonic()
            self._done.clear()
            self._task = asyncio.get_event_loop().create_task(self._run())
        return self.progress()

    async def wait(self, timeout_s: Optional[float] = None) -> dict:
        """Block until the drain loop finishes (tests / soak)."""
        if self._task is not None:
            await asyncio.wait_for(self._done.wait(), timeout_s)
        return self.progress()

    def progress(self) -> dict:
        me = None
        if self.node.membership is not None:
            me = self.node.membership.members.get(self.node.name)
        elapsed = (time.monotonic() - self._started_mono
                   if self._started_mono is not None else 0.0)
        return {
            "state": self.state,
            "lifecycle": me.lifecycle if me is not None else "unknown",
            "queues_total": self.queues_total,
            "queues_moved": self.queues_moved,
            "retries": self.retries,
            "failed": list(self.failed),
            "pinned": list(self.pinned),
            "current": self.current,
            "elapsed_s": round(elapsed, 3),
            "budget_s": self.budget_s,
            "overdue": bool(self.drain_overdue()),
        }

    def drain_overdue(self) -> float:
        """1.0 while a drain has blown its evacuation budget without
        finishing — the telemetry probe behind the drain-stuck alert."""
        if self.state == "stuck":
            return 1.0
        if self.state != "draining" or self._started_mono is None:
            return 0.0
        return 1.0 if (time.monotonic() - self._started_mono
                       > self.budget_s) else 0.0

    def evacuation_log_bytes(self) -> bytes:
        """Canonical serialization of the evacuation log — the form the
        elasticity soak byte-compares across same-seed runs."""
        return "\n".join(
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in self.log_entries
        ).encode()

    # ------------------------------------------------------------------
    # evacuation loop
    # ------------------------------------------------------------------

    def _held_queues(self) -> list[tuple[str, str]]:
        """Queues this node currently holds AND has materialized, in a
        deterministic order."""
        node = self.node
        held = []
        for (vhost, name), meta in node.queue_metas.items():
            if meta.get("holder") != node.name:
                continue
            vh = node.broker.vhosts.get(vhost)
            queue = vh.queues.get(name) if vh is not None else None
            if queue is None or queue.deleted:
                continue
            if queue.exclusive_owner is not None:
                continue  # dies with its connection, never clustered
            held.append((vhost, name))
        return sorted(held)

    def _targets_for(self, vhost: str, name: str) -> list[str]:
        """Evacuation targets, best first: replica followers already
        holding a synced copy, then the ring's preference order, then any
        remaining placement-eligible member. Draining/left peers are
        never targets."""
        node = self.node
        membership = node.membership
        assert membership is not None
        eligible = [m for m in membership.placement_members()
                    if m != node.name]
        ordered: list[str] = []
        if node.replication is not None:
            repl = node.replication._logs.get((vhost, name))
            if repl is not None:
                followers = sorted(repl.followers.items(),
                                   key=lambda kv: (-kv[1], kv[0]))
                ordered.extend(n for n, _acked in followers
                               if n in eligible)
        for pref in node.ring.preference_entity(
                "q", vhost, name, len(eligible) + 1):
            if pref in eligible and pref not in ordered:
                ordered.append(pref)
        for member in eligible:
            if member not in ordered:
                ordered.append(member)
        return ordered

    async def _confirm_barrier(self, queue) -> bool:
        """Release everything a client could have been promised before
        the move: outstanding deliveries settle (bounded), coalesced
        store buffers land, the group commit flushes (publisher confirms
        + stream-cursor commits ride it), and live replication followers
        ack the log head."""
        node = self.node
        deadline = time.monotonic() + self.settle_timeout_s
        while queue.outstanding and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if queue.outstanding:
            return False  # unsettled deliveries: not movable this pass
        queue.flush_store_buffers()
        await node.broker.store.flush(None)
        if node.replication is not None:
            await node.replication.sync_barrier()
        return True

    async def _evacuate_one(self, vhost: str, name: str) -> str:
        """Move one queue off this node: 'moved' | 'pinned' | 'failed'."""
        node = self.node
        vh = node.broker.vhosts.get(vhost)
        queue = vh.queues.get(name) if vh is not None else None
        if queue is None or queue.deleted \
                or node.queue_metas.get((vhost, name), {}).get("holder") \
                != node.name:
            return "moved"  # already gone (raced with a rebalance)
        if queue.is_stream:
            return "pinned"  # the segment log lives in this node's store
        from .node import RemoteConsumer

        if any(not isinstance(c, RemoteConsumer) for c in queue.consumers):
            return "pinned"  # local AMQP consumers cannot follow the queue
        if not await self._confirm_barrier(queue):
            return "failed"  # outstanding deliveries never settled
        targets = self._targets_for(vhost, name)
        if not targets:
            return "failed"
        delay = self.backoff_s
        for attempt in range(self.retry_limit):
            if chaos.ACTIVE is not None:
                # the kill-during-drain seam: a crash rule here takes the
                # node down with the evacuation half done
                await chaos.ACTIVE.fire("drain.tick", peer=node.name)
            target = targets[attempt % len(targets)]
            if await node.handoff_queue(vhost, name, target,
                                        decision=f"drain:{vhost}/{name}"):
                node.broker.metrics.lifecycle_queues_evacuated += 1
                self.log_entries.append({
                    "event": "evacuate", "vhost": vhost, "queue": name,
                    "target": target, "attempt": attempt + 1, "ok": True,
                })
                return "moved"
            self.retries += 1
            await asyncio.sleep(min(delay, self.backoff_cap_s))
            delay *= 2
        self.log_entries.append({
            "event": "evacuate", "vhost": vhost, "queue": name,
            "target": targets[0], "attempt": self.retry_limit, "ok": False,
        })
        return "failed"

    async def _run(self) -> None:
        node = self.node
        broker = node.broker
        try:
            broker.draining = True
            if node.membership is not None:
                node.membership.set_lifecycle(DRAINING)
            log.info("%s: drain started", node.name)
            deadline = time.monotonic() + self.budget_s
            first_pass = True
            while True:
                held = self._held_queues()
                if first_pass:
                    self.queues_total = len(held)
                    first_pass = False
                self.failed = []
                self.pinned = []
                progressed = False
                for vhost, name in held:
                    self.current = f"{vhost}/{name}"
                    outcome = await self._evacuate_one(vhost, name)
                    if outcome == "moved":
                        self.queues_moved += 1
                        progressed = True
                    elif outcome == "pinned":
                        self.pinned.append(f"{vhost}/{name}")
                    else:
                        self.failed.append(f"{vhost}/{name}")
                self.current = None
                if not self.failed:
                    break
                if not progressed and time.monotonic() >= deadline:
                    break
                await asyncio.sleep(min(self.backoff_s,
                                        self.backoff_cap_s))
            if not self.failed and not self.pinned:
                if node.membership is not None:
                    node.membership.set_lifecycle(LEFT)
                self._set_state("drained")
                log.info("%s: drain complete (%d queues evacuated)",
                         node.name, self.queues_moved)
            else:
                self._set_state("stuck")
                log.warning(
                    "%s: drain stuck (%d moved, failed=%s, pinned=%s)",
                    node.name, self.queues_moved, self.failed, self.pinned)
        except asyncio.CancelledError:
            self._set_state("stuck")
            raise
        except Exception:
            self._set_state("stuck")
            log.exception("%s: drain loop crashed", node.name)
        finally:
            self._done.set()
